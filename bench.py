"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Benches the flagship training path on the available accelerator (one real TPU
chip under the driver; CPU otherwise). Metric matches BASELINE.md tracked
metric 1: ResNet-50 train-step throughput, images/sec/chip, vs the north-star
8,000 img/s/chip (BASELINE.json). Falls back to LeNet-5 MNIST throughput if
the zoo model is unavailable.

Methodology: synthetic data (no input-pipeline noise) staged on device ONCE;
several warmup steps to ride out every XLA compile (committed-vs-uncommitted
operand shardings cause up to three traces on the first calls); then timed
steady-state steps, with completion forced by fetching the final scalar loss
to the host (a device→host dependency — block_until_ready alone does not
guarantee completion through the remote-chip tunnel). Measures the whole
jitted train step: forward, reverse AD, updater, parameter write, on device.
bfloat16 compute (fp32 params/accumulation) — the MXU-native policy.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

NORTH_STAR_IMG_PER_SEC = 8000.0  # BASELINE.json north_star, TPU v5e per chip


def _bench_net(net, x, y, steps: int, min_seconds: float = 2.0):
    import jax

    x = jax.device_put(x)
    y = jax.device_put(y)
    for _ in range(4):  # warm past every recompile (sharding commitment)
        net._fit_batch(x, y)
    float(net.score_value)  # force completion of the warmup chain
    t0 = time.perf_counter()
    done = 0
    while done < steps or (time.perf_counter() - t0) < min_seconds:
        net._fit_batch(x, y)
        done += 1
        if done >= steps * 10:
            break
    float(net.score_value)  # host fetch: waits for the full step chain
    dt = time.perf_counter() - t0
    return done * x.shape[0] / dt


def bench_resnet50(batch: int, image: int, steps: int):
    from deeplearning4j_tpu.zoo import ResNet50

    net = ResNet50(num_classes=1000, input_shape=(image, image, 3),
                   compute_dtype="bfloat16").init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, image, image, 3)).astype(np.float32)
    labels = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)]
    ips = _bench_net(net, x, y=labels, steps=steps)
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / NORTH_STAR_IMG_PER_SEC, 4),
    }


def bench_lenet(batch: int, steps: int):
    import __graft_entry__ as ge

    net = ge._flagship()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    ips = _bench_net(net, x, y=labels, steps=steps)
    return {
        "metric": "lenet_mnist_train_images_per_sec",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": 0.0,  # no reference number recorded (BASELINE.md)
    }


def main():
    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # Smaller config on CPU so the bench finishes; real sizes on the chip.
    batch = 256 if on_tpu else 8
    image = 224 if on_tpu else 64
    steps = 20 if on_tpu else 3
    try:
        result = bench_resnet50(batch=batch, image=image, steps=steps)
    except Exception as e:  # zoo not built yet / OOM: fall back
        print(f"resnet50 bench unavailable ({type(e).__name__}: {e}); "
              "falling back to LeNet", file=sys.stderr)
        result = bench_lenet(batch=512 if on_tpu else 64, steps=steps)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
