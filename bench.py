"""Driver benchmark: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra_metrics": [...]}.

The headline metric stays BASELINE.md tracked metric 1 (ResNet-50 train-step
images/sec/chip vs the 8,000 img/s/chip north star). ``extra_metrics`` carries
the other two tracked metrics so every round records all three driver-side
(VERDICT r1 weak #2):
  2. BERT-base fine-tune samples/sec (batch 32, seq 128, bf16, native encoder)
  3. data-parallel scaling curve 1->8 devices. No multi-chip hardware is
     attached, so this runs in a subprocess on a virtual 8-device CPU mesh
     (XLA_FLAGS=--xla_force_host_platform_device_count=8) — it measures the
     sharding program's parallel efficiency shape, not chip ICI.

Methodology per metric: synthetic data staged on device ONCE; warmup past all
XLA recompiles; timed steady-state steps; completion forced by fetching the
final scalar loss to the host (block_until_ready alone does not synchronize
through the remote-chip tunnel). The whole jitted train step is measured:
forward, reverse AD, updater, parameter write. bfloat16 compute with fp32
accumulation — the MXU-native policy. EVERY metric is median-of-3 with an
explicit ``noise`` field (half the min-max spread over the median — the DP
proxy's r4 definition, extended to all metrics per VERDICT r5 weak #2).
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

NORTH_STAR_IMG_PER_SEC = 8000.0  # BASELINE.json north_star, TPU v5e per chip


def _med3(measure, runs: int = 3):
    """median-of-N measurement + spread (VERDICT r5 weak #2: EVERY bench
    metric carries an explicit noise field, not just the DP proxy). Returns
    (median, noise_string); noise = half the min-max spread over the median,
    the same definition the DP proxy has used since r4."""
    vals = sorted(measure() for _ in range(runs))
    med = vals[runs // 2]
    noise = (vals[-1] - vals[0]) / 2.0 / med if med else 0.0
    return med, f"±{round(100 * noise, 1)}% ({runs}-sample spread/2)"


def _bench_net(net, x, y, steps: int, min_seconds: float = 2.0):
    import jax

    x = jax.device_put(x)
    y = jax.device_put(y)
    for _ in range(4):  # warm past every recompile (sharding commitment)
        net._fit_batch(x, y)
    float(net.score_value)  # force completion of the warmup chain
    t0 = time.perf_counter()
    done = 0
    while done < steps or (time.perf_counter() - t0) < min_seconds:
        net._fit_batch(x, y)
        done += 1
        if done >= steps * 10:
            break
    float(net.score_value)  # host fetch: waits for the full step chain
    dt = time.perf_counter() - t0
    return done * x.shape[0] / dt


def bench_resnet50(batch: int, image: int, steps: int):
    from deeplearning4j_tpu.zoo import ResNet50

    net = ResNet50(num_classes=1000, input_shape=(image, image, 3),
                   compute_dtype="bfloat16").init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, image, image, 3)).astype(np.float32)
    labels = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, size=batch)]
    ips, noise = _med3(lambda: _bench_net(net, x, y=labels, steps=steps))
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "model": f"zoo.ResNet50 {image}px classes=1000 B={batch} bf16",
        "value": round(ips, 2),
        "noise": noise,
        "unit": "images/sec/chip",
        # vs the 8,000 img/s/chip v5e north star (BASELINE.json); this chip's
        # measured conv ceiling puts the derated roof far lower — BASELINE.md.
        "vs_baseline": round(ips / NORTH_STAR_IMG_PER_SEC, 4),
    }


def bench_bert(batch: int, seq: int, steps: int, tiny: bool = False):
    """Tracked metric 2: BERT-base fine-tune samples/sec (BASELINE config #4,
    native encoder — one jitted train step; the TF-import route produces the
    same compiled program shape)."""
    from deeplearning4j_tpu.zoo.bert import Bert

    model = (Bert.tiny if tiny else Bert.base)(
        task="classification", num_classes=2, max_length=seq,
        compute_dtype="bfloat16")
    net = model.init()
    rng = np.random.default_rng(0)
    tok = rng.integers(0, model.vocab_size, size=(batch, seq))
    seg = np.zeros((batch, seq))
    x = np.stack([tok, seg], axis=-1).astype(np.int32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=batch)]
    sps, noise = _med3(lambda: _bench_net(net, x, y=labels, steps=steps))
    return {
        "metric": "bert_base_finetune_samples_per_sec_per_chip",
        "model": f"zoo.bert.Bert.{'tiny' if tiny else 'base'} B={batch} seq={seq} bf16",
        "value": round(sps, 2),
        "noise": noise,
        "unit": "samples/sec/chip",
        "vs_baseline": None,  # no reference number exists (BASELINE.md)
    }


_SCALING_CHILD = r"""
import json, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh
from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.zoo import ResNet50

# Fixed GLOBAL batch: the unsharded step and the 8-way-sharded step do the
# same total work on the same host cores, so efficiency = TP8/TP1 isolates
# the cost the SPMD partitioner adds (collectives, halo, reshards). The model
# is the tracked flagship (zoo ResNet-50, shrunk to 32px so the single-core
# CPU host finishes; same graph topology / collective structure as 224px).
# On real multi-chip hardware this same harness measures true scaling.
def throughput(n_dev, global_batch=64, steps=4):
    net = ResNet50(num_classes=16, input_shape=(32, 32, 3)).init()
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(global_batch, 32, 32, 3)).astype(np.float32)
    ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, global_batch)]
    it = ArrayDataSetIterator(xs, ys, batch=global_batch)
    w = ParallelWrapper(net, mesh=TrainingMesh(data=n_dev, devices=jax.devices()[:n_dev]))
    w.fit(it, epochs=1)  # warm past compile
    t0 = time.perf_counter()
    for _ in range(steps):
        w.fit(it, epochs=1)
    jax.block_until_ready(jax.tree_util.tree_leaves(net.params)[0])
    return global_batch * steps / (time.perf_counter() - t0)

# median-of-3 (VERDICT r3 weak #1): single samples on the 1-core host swing
# ±15% with scheduler noise — report the median efficiency and the spread
effs, pairs = [], []
for _ in range(3):
    t1 = throughput(1)
    t8 = throughput(8)
    effs.append(t8 / t1)
    pairs.append((t1, t8))
effs.sort()
med = effs[1]
noise = (effs[-1] - effs[0]) / 2.0 / med if med else 0.0
print(json.dumps({"pairs": pairs, "efficiencies": effs, "efficiency": med,
                  "noise_frac": round(noise, 4)}))
"""


def bench_scaling():
    """Tracked metric 3 proxy: SPMD partitioning efficiency of the flagship
    (zoo ResNet-50) DP train step on a virtual 8-device CPU mesh at fixed
    global batch (sharded vs unsharded throughput on the same host cores).
    True 8->256 chip scaling needs the hardware this environment does not
    attach; the single-core host further depresses the absolute number (see
    BASELINE.md) — only the same-host trend is meaningful."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _SCALING_CHILD], env=env,
                         capture_output=True, text=True, timeout=1500,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    line = [l for l in out.stdout.strip().splitlines() if l.startswith("{")][-1]
    r = json.loads(line)
    return {
        "metric": "dp_sharding_efficiency_8dev_virtual_cpu",
        "model": "zoo.ResNet50 32px classes=16 global_batch=64 fp32 (flagship topology, CPU-sized)",
        "value": round(r["efficiency"], 4),  # median of 3
        "noise": f"±{round(100 * r.get('noise_frac', 0), 1)}% (3-sample spread/2, 1-core host)",
        "unit": "fraction",
        "vs_baseline": round(r["efficiency"] / 0.90, 4),  # ≥90% north star
    }


_ZERO_MEMORY_CHILD = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh, gspmd

# ~25M params with Adam -> ~202 MB of fp32 moments replicated per device;
# ZeRO shards every 8-divisible moment leaf over the 'data' axis
conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_in=2048, n_out=4096, activation="relu"))
        .layer(DenseLayer(n_in=4096, n_out=4096, activation="relu"))
        .layer(OutputLayer(n_in=4096, n_out=16, loss="mcxent",
                           activation="softmax"))
        .set_input_type(InputType.feed_forward(2048)).build())
net = MultiLayerNetwork(conf).init()
replicated = gspmd.tree_bytes(net.opt_states)
pw = ParallelWrapper(net, mesh=TrainingMesh(data=8), zero_optimizer=True,
                     skew_every=0)
rng = np.random.default_rng(0)
xs = rng.standard_normal((16, 2048)).astype(np.float32)
ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, 16)]
pw.fit([DataSet(xs, ys)], epochs=1)  # build + one real step
per_dev = pw.opt_state_bytes_per_device()
print(json.dumps({"per_device": int(per_dev), "replicated": int(replicated),
                  "ratio": per_dev / replicated,
                  "sharded_fraction": gspmd.sharded_fraction(pw._zero_specs)}))
"""


def bench_zero_memory():
    """ZeRO satellite metric: optimizer-state bytes ONE device holds for
    the 25M-param Adam net on the 8-virtual-device mesh (arXiv:2004.13336
    cross-replica weight-update sharding). Replicated baseline is the same
    tree's full footprint; the ratio is the honest ~1/N claim. Runs in a
    subprocess so the 8-device CPU topology never leaks into the parent
    (which may hold the real chip)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _ZERO_MEMORY_CHILD], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    line = [l for l in out.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    r = json.loads(line)
    return {
        "metric": "zero_optimizer_memory_bytes_per_device",
        "model": (f"25M-param dense Adam, 8-dev ZeRO "
                  f"(replicated {r['replicated']} B, ratio "
                  f"{r['ratio']:.4f}, sharded fraction "
                  f"{r['sharded_fraction']:.2f})"),
        "value": r["per_device"],
        "unit": "bytes/device",
        "vs_baseline": round(r["ratio"], 4),  # vs replicated footprint
    }


_PIPELINE_CHILD = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import PipelinedTrainer, TrainingMesh, gspmd

# stage-dominated net (4 x 1024x1024 dense stage layers + Adam moments):
# replicated param+opt footprint ~50 MB; the (data=2, model=2, pipe=2)
# placement pipe-shards the stacked stage params and ZeRO-shards the
# moments over 'data' — bytes ONE device holds is the gated number
STAGES, N_MICRO = 2, 4
W = 1024
conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
        .pipe_stages(STAGES).n_micro(N_MICRO).list()
        .layer(DenseLayer(n_in=256, n_out=W, activation="relu"))
        .stage_boundary()
        .layer(DenseLayer(n_in=W, n_out=W, activation="tanh"))
        .layer(DenseLayer(n_in=W, n_out=W, activation="relu"))
        .stage_boundary()
        .layer(DenseLayer(n_in=W, n_out=W, activation="tanh"))
        .layer(DenseLayer(n_in=W, n_out=W, activation="relu"))
        .stage_boundary()
        .layer(OutputLayer(n_in=W, n_out=16, loss="mcxent",
                           activation="softmax"))
        .set_input_type(InputType.feed_forward(256)).build())
net = MultiLayerNetwork(conf).init()
replicated = gspmd.tree_bytes(net.params) + gspmd.tree_bytes(net.opt_states)
pt = PipelinedTrainer(net, mesh=TrainingMesh(data=2, model=2, pipe=2),
                      replicas=2, skew_every=0)
rng = np.random.default_rng(0)
xs = rng.standard_normal((16, 256)).astype(np.float32)
ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, 16)]
pt.fit([DataSet(xs, ys)], epochs=1)  # build + one real pipelined step
per_dev = pt.train_state_bytes_per_device()
print(json.dumps({
    "per_device": int(per_dev), "replicated": int(replicated),
    "ratio": per_dev / replicated, "stages": STAGES, "n_micro": N_MICRO,
    "bubble": pt.bubble_fraction,
    "param_per_device": int(pt.param_bytes_per_device()),
    "opt_per_device": int(pt.opt_state_bytes_per_device()),
    "loss_finite": bool(np.isfinite(float(net.score_value)))}))
"""


def bench_pipeline():
    """Pipeline-parallel fit() metrics (ISSUE 14, BENCH_r10 headline):
    ``pipeline_param_bytes_per_device`` — param+optimizer bytes ONE device
    holds for the stage-dominated net on the (data=2, model=2, pipe=2)
    8-virtual-device mesh (stacked stage params P('pipe'), moments
    ZeRO-sharded; the "model too big for one chip as a config knob"
    number) — and ``pipeline_bubble_fraction`` — the GPipe fill-drain
    schedule's idle fraction (S-1)/(n_micro+S-1) at the committed
    (stages=2, n_micro=4) config. Both are DETERMINISTIC byte/schedule
    accounting: CPU proves placement, equivalence, and the schedule's
    arithmetic, it cannot rank pipelined wall-clock (bubbles only cost
    time on real chips — the r6 convention; docs/DISTRIBUTED.md)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _PIPELINE_CHILD], env=env,
                         capture_output=True, text=True, timeout=1500,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    line = [l for l in out.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    r = json.loads(line)
    assert r["loss_finite"], r
    return [
        {
            "metric": "pipeline_param_bytes_per_device",
            "model": (f"4x{1024}-wide stage-dominated Adam net on "
                      f"(data=2, model=2, pipe=2), stages={r['stages']} "
                      f"(replicated {r['replicated']} B, params/dev "
                      f"{r['param_per_device']} B + opt/dev "
                      f"{r['opt_per_device']} B, ratio {r['ratio']:.4f} "
                      f"≈ 1/pipe_stages; deterministic byte accounting — "
                      f"CPU proves placement+equivalence, cannot rank "
                      f"pipelined wall-clock)"),
            "value": r["per_device"],
            "noise": "±0.0% (deterministic byte accounting)",
            "unit": "bytes/device",
            "vs_baseline": round(r["ratio"], 4),  # vs replicated footprint
        },
        {
            "metric": "pipeline_bubble_fraction",
            "model": (f"GPipe fill-drain schedule, stages={r['stages']} "
                      f"n_micro={r['n_micro']}: (S-1)/(n_micro+S-1) — "
                      f"computed from the schedule, never timed on this "
                      f"CPU container (bubbles cost wall-clock only on "
                      f"real chips)"),
            "value": round(r["bubble"], 6),
            "noise": "±0.0% (schedule arithmetic)",
            "unit": "fraction",
            # vs the degenerate n_micro=1 schedule at S=2:
            # (S-1)/(1+S-1) = 0.5 — the no-microbatching worst case
            "vs_baseline": round(r["bubble"] / 0.5, 4),
        },
    ]


_COMPRESSION_CHILD = r"""
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh

# the ZeRO bench's 25M-param Adam topology — the DP workload whose gradient
# exchange the encoded all-reduce compresses (ISSUE 10 acceptance: ratio
# <= 0.1 at the adaptive target sparsity)
def build(comp):
    b = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3)))
    if comp:
        b = b.grad_compression("threshold", threshold=1e-3,
                               target_sparsity=1e-3)
    conf = (b.list()
            .layer(DenseLayer(n_in=2048, n_out=4096, activation="relu"))
            .layer(DenseLayer(n_in=4096, n_out=4096, activation="relu"))
            .layer(OutputLayer(n_in=4096, n_out=16, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(2048)).build())
    return MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)
xs = rng.standard_normal((16, 2048)).astype(np.float32)
ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, 16)]
ds = [DataSet(xs, ys)]

def timed_fit(comp, steps=12):
    net = build(comp)
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=8), skew_every=0,
                         grad_compression=None)
    pw.fit(ds, epochs=2)  # compile + settle the adaptive threshold
    t0 = time.perf_counter()
    pw.fit(ds, epochs=steps)
    jax.block_until_ready(jax.tree_util.tree_leaves(net.params)[0])
    dt = time.perf_counter() - t0
    stats = pw.compression_stats() if comp else None
    return dt, stats, float(net.score_value)

dt_comp, stats, loss_c = timed_fit(True)
dt_exact, _, loss_e = timed_fit(False)
print(json.dumps({
    "ratio": stats["ratio"], "wire_bytes": stats["wire_bytes"],
    "dense_bytes": stats["dense_bytes"], "threshold": stats["threshold"],
    "nnz": stats["nnz"], "elements": stats["elements"],
    "compressed_step_seconds": dt_comp / 12,
    "exact_step_seconds": dt_exact / 12,
    "loss_compressed": loss_c, "loss_exact": loss_e,
}))
"""


def bench_compression_ratio():
    """encoded_allreduce_wire_bytes_ratio: deterministic wire accounting of
    the encoded gradient all-reduce (parallel/compression.py) on the
    25M-param DP workload — one worker's sparse threshold payload vs its
    dense fp32 gradient, at the adaptive target sparsity (1e-3). The byte
    math is exact and CPU-provable; the wall-clock A/B rides along in the
    model string but CANNOT rank the paths on this container (the encode
    costs CPU FLOPs while the wire savings only pay on a real DCN — the r6
    convention; docs/DISTRIBUTED.md#gradient-compression)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _COMPRESSION_CHILD], env=env,
                         capture_output=True, text=True, timeout=1500,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    line = [l for l in out.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    r = json.loads(line)
    return {
        "metric": "encoded_allreduce_wire_bytes_ratio",
        "model": (f"25M-param dense Adam DP, 8-dev, threshold scheme @ "
                  f"target 1e-3 (wire {r['wire_bytes']:.0f} B vs dense "
                  f"{r['dense_bytes']:.0f} B; adapted threshold "
                  f"{r['threshold']:.2e}; CPU step A/B compressed "
                  f"{r['compressed_step_seconds']:.3f}s vs exact "
                  f"{r['exact_step_seconds']:.3f}s — CPU cannot rank, "
                  f"encode costs FLOPs here while wire savings pay on DCN)"),
        "value": round(r["ratio"], 6),
        "unit": "fraction",
        "vs_baseline": round(r["ratio"] / 0.1, 4),  # <= 0.1 acceptance
    }


_TP_BERT_CHILD = r"""
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from jax.sharding import PartitionSpec as P
from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh
from deeplearning4j_tpu.zoo.bert import Bert

B, SEQ = 32, 64
model = Bert.tiny(task="classification", num_classes=2, max_length=SEQ)
net = model.init()
mesh = TrainingMesh(data=4, model=2)
# Megatron-style annotation (SNIPPETS.md [3]): attention QKV + FFN-in are
# column-sharded, the output projections row-sharded; everything else
# (embeddings, norms, head) stays replicated — XLA inserts the TP
# collectives from the annotations alone
net.params = mesh.tensor_shard_params(net.params, [
    (r"\['W[qkv]'\]$", P(None, "model")),
    (r"\['Wo'\]$", P("model", None)),
    (r"\['W1'\]$", P(None, "model")),
    (r"\['W2'\]$", P("model", None)),
])
rng = np.random.default_rng(0)
tok = rng.integers(0, model.vocab_size, size=(B, SEQ))
seg = np.zeros((B, SEQ))
x = np.stack([tok, seg], axis=-1).astype(np.int32)
y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=B)]
it = ArrayDataSetIterator(x, y, batch=B)
pw = ParallelWrapper(net, mesh=mesh, skew_every=0)
pw.fit(it, epochs=1)  # compile
steps = 6
t0 = time.perf_counter()
pw.fit(it, epochs=steps)
jax.block_until_ready(jax.tree_util.tree_leaves(net.params)[0])
dt = time.perf_counter() - t0
n_tp = sum(1 for v in jax.tree_util.tree_leaves(net.params)
           if hasattr(v, "sharding") and any(getattr(v.sharding, "spec", ()) or ()))
print(json.dumps({"samples_per_sec": B * steps / dt, "tp_sharded_leaves": n_tp}))
"""


def bench_tp_bert_smoke():
    """Tensor-parallel smoke on the ("data","model") 2-D mesh — the new
    axis gets a number from day one. BERT (CPU-sized tiny config; the same
    annotation rules apply to base on the chip) with Megatron-style
    NamedSharding on QKV/FFN kernels, 4x2 virtual-device mesh."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _TP_BERT_CHILD], env=env,
                         capture_output=True, text=True, timeout=900,
                         cwd=os.path.dirname(os.path.abspath(__file__)))
    line = [l for l in out.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    r = json.loads(line)
    if r["tp_sharded_leaves"] == 0:
        raise RuntimeError("no tensor-parallel leaves were sharded")
    return {
        "metric": "tp_bert_smoke_samples_per_sec",
        "model": (f"zoo.bert.Bert.tiny B=32 seq=64 on (data=4, model=2) "
                  f"virtual CPU mesh, {r['tp_sharded_leaves']} TP-sharded "
                  "param leaves"),
        "value": round(r["samples_per_sec"], 2),
        "unit": "samples/sec",
        "vs_baseline": None,  # first number on this axis
    }


def bench_attention_2k(batch: int = 4, seq: int = 2048, k_lo: int = 8,
                       k_hi: int = 40):
    """Extra metric (VERDICT r2 #5): seq-2048 flash-attention fwd+bwd token
    throughput — the regime where the Pallas kernel earns its keep (measured
    crossover table in BASELINE.md). TWO-POINT FIT (BASELINE.md round-4
    methodology): time K-iteration scans at two K inside one jit each and
    take (wall(K_hi) - wall(K_lo)) / (K_hi - K_lo), cancelling the
    session-variable tunnel round-trip latency (measured 4-135 ms across
    sessions) that a single-call timing would fold into every iteration."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.attention import flash_attention

    H, D = 12, 64
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.normal(size=(batch, H, seq, D)).astype(np.float32)
    ).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v, s):
        return jnp.sum(flash_attention(q + s, k, v).astype(jnp.float32))

    g = jax.value_and_grad(loss, argnums=(0, 1, 2))

    def make_many(iters):
        @jax.jit
        def many(q, k, v):
            def body(c, s):
                val, grads = g(q, k, v, s.astype(jnp.bfloat16))
                return c + val + sum(jnp.sum(x).astype(jnp.float32)
                                     for x in grads), None

            out, _ = jax.lax.scan(
                body, jnp.float32(0),
                jnp.arange(iters, dtype=jnp.float32) * 1e-6)
            return out
        return many

    def timed(fn):
        float(fn(q, k, v))  # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(fn(q, k, v))
            best = min(best, time.perf_counter() - t0)
        return best

    lo_fn, hi_fn = make_many(k_lo), make_many(k_hi)

    def one_fit():
        for _ in range(3):  # jitter can make t_hi <= t_lo; retry, never clamp
            t_lo = timed(lo_fn)
            t_hi = timed(hi_fn)
            if t_hi > t_lo:
                return (t_hi - t_lo) / (k_hi - k_lo)
        raise RuntimeError(
            f"two-point fit invalid after retries (t_lo={t_lo:.4f}s >= "
            f"t_hi={t_hi:.4f}s): session latency noise exceeds the "
            "device-time delta; not reporting a corrupted number")

    dt, noise = _med3(one_fit)
    return {
        "metric": "flash_attention_seq2048_tokens_per_sec",
        "model": f"flash fwd+bwd B={batch} H={H} S={seq} D={D} bf16",
        "value": round(batch * seq / dt),
        "noise": noise,
        "unit": "tokens/sec",
        "vs_baseline": None,  # no reference number exists (BASELINE.md)
    }


def bench_lstm_char_rnn(batch: int = 128, seq: int = 128, vocab: int = 96,
                        hidden: int = 512, steps: int = 60):
    """Tracked metric 4 (BASELINE config #3): GravesLSTM-class char-RNN
    train-step tokens/sec — 2xLSTM(H) + RnnOutputLayer, one-hot inputs,
    bf16. Methodology: many steps in flight, completion forced by the final
    score fetch (the per-step dispatch pipeline amortizes the tunnel
    latency; XPlane-verified 7.87 ms/step device time at this config,
    BASELINE.md round-4 table)."""
    import jax

    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .compute_dtype("bfloat16").list()
            .layer(LSTM(n_in=vocab, n_out=hidden))
            .layer(LSTM(n_in=hidden, n_out=hidden))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab))
            .set_input_type(InputType.recurrent(vocab, seq))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = jax.device_put(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, seq))])
    y = jax.device_put(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, seq))])
    for _ in range(4):
        net._fit_batch(x, y)
    float(net.score_value)

    def one_run():
        t0 = time.perf_counter()
        for _ in range(steps):
            net._fit_batch(x, y)
        float(net.score_value)
        return (time.perf_counter() - t0) / steps

    dt, noise = _med3(one_run)
    return {
        "metric": "lstm_char_rnn_train_tokens_per_sec",
        "model": f"2xLSTM(H={hidden}) char-RNN B={batch} T={seq} V={vocab} bf16",
        "value": round(batch * seq / dt),
        "noise": noise,
        "unit": "tokens/sec",
        "vs_baseline": None,  # no reference number exists (BASELINE.md)
    }


def _build_lenet(seed: int = 0, sync_every: int = 1):
    """LeNet-5 MNIST on the nn DSL, zoo-independent (shared by the fallback
    metric and the host-pipeline overlap metric)."""
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
        .sync_every(sync_every).list()
        .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_in=500, n_out=10))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def bench_lenet(batch: int, steps: int):
    """Fallback metric (BASELINE config #1): LeNet-5 MNIST built directly on
    the nn DSL — deliberately independent of the zoo, because this path runs
    exactly when the flagship zoo model is what broke (VERDICT r5 weak #3:
    the old fallback built ResNet-50 via the zoo and fed it MNIST shapes, so
    it crashed whenever it was needed)."""
    net = _build_lenet()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    ips, noise = _med3(lambda: _bench_net(net, x, y=labels, steps=steps))
    return {
        "metric": "lenet_mnist_train_images_per_sec",
        "model": f"LeNet-5 MNIST B={batch} (nn DSL, zoo-independent)",
        "value": round(ips, 2),
        "noise": noise,
        "unit": "images/sec",
        "vs_baseline": None,  # no reference number exists (BASELINE.md)
    }


class _SlowIterator:
    """DataSetIterator facade injecting a fixed ETL delay per batch — the
    A/B load for the host-pipeline overlap metric (sleep-based = I/O-shaped
    ETL; a CPU-bound transform could not overlap on this 1-core host —
    docs/HOST_PIPELINE.md measurement-ceiling note)."""

    def __init__(self, base, delay_s: float):
        self.base = base
        self.delay_s = delay_s

    def __iter__(self):
        for ds in self.base:
            time.sleep(self.delay_s)
            yield ds

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def batch_size(self):
        return self.base.batch_size()


def bench_host_pipeline(batch: int = 64, n_batches: int = 12):
    """host_pipeline_overlap: LeNet-5 fit wall-time under an injected slow
    transform divided by compute-only wall-time. Serial feeding pays
    compute + ETL per step (ratio ≈ 2× when the injected delay equals the
    step time); the device-prefetch iterator (AsyncDataSetIterator,
    sync_every>1 orchestration) overlaps ETL + device_put of batch k+1 under
    batch k's compute — target ≤ 1.15×. Median-of-3 on the RATIOS with the
    standard noise field; the serial ratio is reported alongside so both
    ends of the A/B are in the table (ISSUE 2 acceptance)."""
    import jax

    from deeplearning4j_tpu.data import (ArrayDataSetIterator,
                                         AsyncDataSetIterator)

    net = _build_lenet(sync_every=max(2, n_batches // 2))

    class _Observer:  # a listener must be installed for the coalesced
        count = 0     # dispatch path to be IN the measured loop (with no
                      # listeners the dispatcher skips the fetch entirely)
        def iteration_done(self, model, iteration, epoch):
            self.count += 1

    net.set_listeners(_Observer())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * n_batches, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, len(x))]
    xd, yd = jax.device_put(x[:batch]), jax.device_put(y[:batch])
    for _ in range(4):  # warm past every recompile
        net._fit_batch(xd, yd)
    float(net.score_value)

    def compute_only():
        t0 = time.perf_counter()
        for _ in range(n_batches):
            net._fit_batch(xd, yd)
        float(net.score_value)
        return time.perf_counter() - t0

    t_step = compute_only() / n_batches
    # 0.8x a step of compute: heavy enough that serial feeding pays ~1.8-2x,
    # light enough that a working overlap can actually hide it — at exactly
    # 1.0x the pipeline is critically balanced and every ms of worker/queue
    # overhead lands in the ratio instead of under the compute
    delay = 0.8 * t_step

    def fit_wall(iterator):
        t0 = time.perf_counter()
        net.fit(iterator, epochs=1)
        float(net.score_value)
        return time.perf_counter() - t0

    def one_run():
        it = lambda: ArrayDataSetIterator(x, y, batch=batch)  # noqa: E731
        t_c = compute_only()
        t_serial = fit_wall(_SlowIterator(it(), delay))
        t_pref = fit_wall(AsyncDataSetIterator(_SlowIterator(it(), delay),
                                               buffer_size=2))
        return t_pref / t_c, t_serial / t_c

    runs = sorted(one_run() for _ in range(3))
    overlap = runs[1][0]
    serial = sorted(r[1] for r in runs)[1]
    spread = (runs[-1][0] - runs[0][0]) / 2.0 / overlap if overlap else 0.0
    return {
        "metric": "host_pipeline_overlap",
        "model": (f"LeNet-5 B={batch} x{n_batches} batches, injected ETL "
                  f"{delay * 1e3:.1f} ms/batch (0.8x step), prefetch "
                  "buffer=2, coalesced sync"),
        "value": round(overlap, 4),
        "noise": f"±{round(100 * spread, 1)}% (3-sample spread/2)",
        "unit": "x compute-only wall (1.0 = ETL fully hidden)",
        "serial_ratio": round(serial, 4),  # the no-prefetch end of the A/B
        # ≤ 1.0 means the ≤1.15x overlap target is met (BASELINE.md)
        "vs_baseline": round(overlap / 1.15, 4),
    }


def bench_telemetry_overhead(batch: int = 64, steps: int = 30):
    """telemetry_overhead: steady-state step time with the FULL observability
    stack on (telemetry spans + step histogram, TrainingHealthMonitor with
    NaN sentinel/update-ratio probe, RecompileListener, coalesced dispatch)
    over step time with telemetry disabled and no listeners — the price of
    watching (docs/OBSERVABILITY.md). Target ≤ 1.05x (ISSUE 4 acceptance).
    Median-of-3 of the ratio with the standard noise field."""
    import jax

    from deeplearning4j_tpu.nn.listeners import RecompileListener
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.util.health import TrainingHealthMonitor

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    net = _build_lenet(sync_every=4)
    xd, yd = jax.device_put(x), jax.device_put(y)
    tele = tm.get_telemetry()

    def timed(enable):
        tele.enabled = enable
        if enable:
            net.set_listeners(TrainingHealthMonitor(window=4, log_fn=None),
                              RecompileListener(log_fn=lambda *a: None))
        else:
            net.set_listeners()
        # warm past recompiles AND two window=4 boundaries, so both probe
        # variants (first-window no-prev and steady with-prev) have traced
        # and compiled before the timed region
        for _ in range(8):
            net._fit_batch(xd, yd)
        net._dispatcher.flush()
        float(net.score_value)
        t0 = time.perf_counter()
        for _ in range(steps):
            net._fit_batch(xd, yd)
        net._dispatcher.flush()
        float(net.score_value)
        return (time.perf_counter() - t0) / steps

    was_enabled = tele.enabled
    try:
        def one_ratio():
            t_off = timed(False)
            t_on = timed(True)
            return t_on / t_off

        ratio, noise = _med3(one_ratio)
    finally:
        tele.enabled = was_enabled
        net.set_listeners()
    return {
        "metric": "telemetry_overhead",
        "model": (f"LeNet-5 B={batch} x{steps} steps, spans + health monitor"
                  " (window=4 NaN sentinel/update-ratio probe) +"
                  " RecompileListener + coalesced dispatch, on vs off"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x untelemetered step time (1.0 = free)",
        # ≤ 1.0 means the ≤ 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }


def bench_cost_attribution(batch: int = 64, steps: int = 30):
    """cost_attribution_overhead: steady-state step time with cost
    attribution ENABLED (a computed+published CostReport priming the
    per-step examples_per_sec / model_flops_utilization gauges, telemetry
    on) over step time with plain telemetry and no attribution — the
    per-step price of knowing where the FLOPs go
    (docs/OBSERVABILITY.md#cost-attribution--mfu). The one-time static
    analysis (lower+compile+HLO parse) runs OUTSIDE the timed region — it
    is a startup cost, reported separately as ``analysis_seconds``. Target
    <= 1.05x; median-of-3 with the standard noise field. Also reports the
    attribution-reconciliation ratio (per-layer FLOPs summed over the XLA
    whole-program total — the tests pin it within 5%)."""
    import jax

    from deeplearning4j_tpu.util import telemetry as tm

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[np.random.default_rng(1).integers(
        0, 10, size=batch)]
    net = _build_lenet()
    xd, yd = jax.device_put(x), jax.device_put(y)
    tele = tm.get_telemetry()
    was_enabled = tele.enabled
    tele.enabled = True

    def timed():
        for _ in range(6):  # warm past every recompile
            net._fit_batch(xd, yd)
        float(net.score_value)
        t0 = time.perf_counter()
        for _ in range(steps):
            net._fit_batch(xd, yd)
        float(net.score_value)
        return (time.perf_counter() - t0) / steps

    try:
        t_an = time.perf_counter()
        # attribution on: published report + an explicit peak so the MFU
        # gauge branch is exercised even without DL4J_TPU_PEAK_FLOPS set
        report = net.cost_report(batch_size=batch, peak_flops=1e12)
        analysis_seconds = time.perf_counter() - t_an
        attributed = sum(r.flops for r in report.rows)
        recon = attributed / report.flops_per_step \
            if report.flops_per_step else None

        def one_ratio():
            # attribution off: same net, gauges disarmed
            net._cost_flops_per_example = None
            net._peak_flops = None
            t_off = timed()
            net._cost_flops_per_example = report.flops_per_step / batch
            net._peak_flops = 1e12
            t_on = timed()
            return t_on / t_off

        ratio, noise = _med3(one_ratio)
    finally:
        tele.enabled = was_enabled
    return {
        "metric": "cost_attribution_overhead",
        "model": (f"LeNet-5 B={batch} x{steps} steps, per-step "
                  "examples/sec + MFU gauges from a published CostReport, "
                  "on vs off (telemetry on both sides)"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x unattributed step time (1.0 = free)",
        "analysis_seconds": round(analysis_seconds, 3),
        "attribution_source": report.source,
        # per-layer FLOPs summed / XLA whole-program total (1.0 = exact)
        "flops_reconciliation": round(recon, 4) if recon else None,
        # <= 1.0 means the <= 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }


def bench_optimizer_update_share(depth: int = 96, width: int = 8,
                                 batch: int = 32, steps: int = 5):
    """optimizer_update_ms_share: the update phase's fraction of attributed
    per-step device time (the ``(optimizer)`` cost-attribution row from a
    profiled ``cost_report()``, docs/OBSERVABILITY.md) with the FUSED
    donated optimizer apply (docs/KERNELS.md#fused-optimizer-apply) on the
    many-leaf workload the per-leaf walk is worst at — a deep narrow Adam
    MLP (2*depth+3 param leaves). LOWER_BETTER, gated by
    benchmarks/regression_gate.py.

    Honesty (r6 convention — the full A/B rides in the record): on
    XLA:CPU the per-leaf update ops FUSE INTO the backward kernels, so the
    per-leaf ``(optimizer)`` row undercounts its true cost and the two
    *shares* are not directly comparable; what IS directly comparable is
    the whole-step wall time, reported as ``fused_step_ms`` /
    ``per_leaf_step_ms`` (measured here: the fused apply makes the WHOLE
    step ~2.4x faster at this config by collapsing ~200 tiny update ops
    into a handful of buffer ops). The gated value is the fused share —
    self-consistent run to run, it keeps the fused update phase from
    regressing. Median-of-3 with the standard noise field."""
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    def build(fused):
        b = NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
        if fused:
            b = b.fused_update(True)
        lb = b.list()
        for _ in range(depth):
            lb = lb.layer(DenseLayer(n_in=width, n_out=width,
                                     activation="relu"))
        lb = lb.layer(OutputLayer(n_in=width, n_out=8))
        conf = lb.set_input_type(InputType.feed_forward(width)).build()
        return MultiLayerNetwork(conf).init()

    def measure(fused):
        net = build(fused)
        rep = net.cost_report(batch_size=batch, profile=True, steps=steps,
                              publish=False)
        s = rep.optimizer_update_share
        if s is None:
            raise RuntimeError(
                "no profiled device-time attribution on this backend — "
                "optimizer_update_ms_share cannot be measured honestly")
        return s, rep.step_time_s * 1e3

    # ONE set of 3 runs per config; share and step-ms medians come from it
    fused_runs = sorted(measure(True) for _ in range(3))
    per_leaf_runs = sorted(measure(False) for _ in range(3))
    fused_share = sorted(r[0] for r in fused_runs)[1]
    per_leaf_share = sorted(r[0] for r in per_leaf_runs)[1]
    fused_ms = sorted(r[1] for r in fused_runs)[1]
    per_leaf_ms = sorted(r[1] for r in per_leaf_runs)[1]
    spread = (fused_runs[-1][0] - fused_runs[0][0]) / 2.0 / fused_share \
        if fused_share else 0.0
    noise = f"±{round(100 * spread, 1)}% (3-sample spread/2)"
    return {
        "metric": "optimizer_update_ms_share",
        "model": (f"deep-narrow Adam MLP depth={depth} width={width} "
                  f"B={batch} ({2 * depth + 3} param leaves), fused "
                  "dtype-grouped resident-buffer apply"),
        "value": round(fused_share, 4),
        "noise": noise,
        "unit": "fraction of attributed device time (LOWER_BETTER)",
        # the honest A/B (per-leaf share undercounts: its update ops fuse
        # into backward kernels on XLA:CPU — see docstring):
        "per_leaf_share": round(per_leaf_share, 4),
        "fused_step_ms": round(fused_ms, 3),
        "per_leaf_step_ms": round(per_leaf_ms, 3),
        # whole-step win of the fused apply at this config (< 1 = faster)
        "vs_baseline": round(fused_ms / per_leaf_ms, 4) if per_leaf_ms
        else None,
    }


def bench_autotune_dispatch(batch: int = 8, calls: int = 150):
    """autotune_dispatch_overhead: per-call time of an eager
    ``kernel_impl=auto`` conv2d whose dispatch CONSULTS the tuning
    database (DL4J_TPU_TUNING_DB armed, a committed winner for this exact
    geometry — tuning/database.py, docs/AUTOTUNE.md) over the hardwired
    ``exact``-pinned dispatch running the identical executable. The
    committed winner IS ``exact``, so both paths execute the same conv —
    the ratio isolates what the database consultation costs at trace/
    dispatch time: one signature f-string + one in-memory-cached lookup.
    Target ≤ 1.05x, wired LOWER_BETTER into benchmarks/regression_gate.py
    (ISSUE 11 acceptance). Median-of-3 with the standard noise field."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import tuning
    from deeplearning4j_tpu.ops import kernels as K
    from deeplearning4j_tpu.ops import nn as nnops
    from deeplearning4j_tpu.ops.kernels import conv as kconv

    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(
        rng.normal(size=(batch, 16, 16, 8)), jnp.float32))
    w = jax.device_put(jnp.asarray(
        rng.normal(size=(3, 3, 8, 16)) * 0.1, jnp.float32))
    sig = kconv.shape_signature(x.shape, w.shape, (1, 1), "SAME", (1, 1), 1)
    db_dir = tempfile.mkdtemp(prefix="dl4j-bench-tuning.")
    db = tuning.set_database(db_dir)
    # a committed exact winner: the DB-consulted path must resolve to the
    # SAME executable as the hardwired path, so the ratio is pure dispatch
    db.commit(tuning.TuningKey.for_op("conv2d", sig, "float32"),
              {"winner": {"label": "exact", "impl": "exact", "params": {},
                          "ms": 0.0, "noise": "n/a"},
               "candidates_digest": "bench-direct-commit",
               "measured": []})

    def timed(scope):
        with K.impl_scope(scope):
            jax.block_until_ready(nnops.conv2d(x, w))   # warm + compile
            t0 = time.perf_counter()
            for _ in range(calls):
                out = nnops.conv2d(x, w)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / calls

    try:
        def one_ratio():
            # min-of-3 per scope inside each sample: the dispatch delta
            # being measured is ~µs against a ~250µs eager call, so the
            # minimum (least scheduler interference) is the stable
            # estimator; the outer median-of-3 still reports honest noise
            t_exact = min(timed("exact") for _ in range(3))
            t_auto = min(timed("auto") for _ in range(3))
            return t_auto / t_exact

        ratio, noise = _med3(one_ratio)
    finally:
        tuning.set_database(None)
        shutil.rmtree(db_dir, ignore_errors=True)
    return {
        "metric": "autotune_dispatch_overhead",
        "model": (f"eager conv2d B={batch} 16x16x8->16 x{calls} calls, "
                  "auto dispatch through a committed tuning-DB winner "
                  "(=exact) vs impl_scope('exact') hardwired"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x hardwired dispatch time (1.0 = free)",
        # ≤ 1.0 means the ≤ 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }


def bench_elastic_overhead(batch: int = 64, steps: int = 40):
    """elastic_overhead: steady-state step time under full ElasticTrainer
    supervision — live heartbeat thread (FileMembership, 100ms cadence),
    periodic ASYNC checkpointing (a commit landing inside the timed
    window), drain-signal handling, and the rollback health monitor — over
    bare fit() step time (docs/FAULT_TOLERANCE.md). Step time is measured
    between the FIRST and LAST iteration_done timestamps of one epoch, so
    the one-time blocking commits at the run's edges (the initial rollback
    target, the final drain save) count as startup/shutdown — reported
    separately as ``checkpoint_seconds`` (the r10 ``analysis_seconds``
    convention) — while the per-step supervision and the in-window async
    commit are exactly what the ratio prices. Target <= 1.05x (ISSUE 6
    acceptance); median-of-3 with the standard noise field."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.listeners import TrainingListener
    from deeplearning4j_tpu.parallel import ElasticTrainer, FileMembership
    from deeplearning4j_tpu.util.checkpoint import ShardedCheckpointer

    from deeplearning4j_tpu.util.health import TrainingHealthMonitor

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * steps, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch * steps)]
    it = lambda: ArrayDataSetIterator(x, y, batch=batch)  # noqa: E731
    net = _build_lenet()
    # ONE monitor shared by every supervised run, warmed here so its jitted
    # NaN-sentinel/update-ratio probes compile outside the timed window
    # (its per-step cost is already priced by telemetry_overhead; what this
    # bench adds on top is heartbeats + checkpointing + supervision)
    monitor = TrainingHealthMonitor(action="rollback", window=10, log_fn=None)
    net.listeners.append(monitor)
    net.fit(it(), epochs=1)  # compile step + both probe variants
    net.listeners.remove(monitor)

    class _Stamps(TrainingListener):
        def __init__(self):
            self.t = []

        def iteration_done(self, model, iteration, epoch):
            # forces the loss fetch (score_value float) like a real
            # listener window boundary would — same cost on both sides
            self.t.append(time.perf_counter())

    work_dir = tempfile.mkdtemp(prefix="dl4j-elastic-bench-")
    try:
        # the run-edge blocking commit, reported separately (startup cost)
        ck = ShardedCheckpointer(os.path.join(work_dir, "probe"), log_fn=None)
        t0 = time.perf_counter()
        ck.save(0, net)
        checkpoint_seconds = time.perf_counter() - t0

        def steady(dts):
            assert len(dts) >= 2
            return (dts[-1] - dts[0]) / (len(dts) - 1)

        def t_plain():
            stamps = _Stamps()
            net.listeners.append(stamps)
            try:
                net.fit(it(), epochs=1)
            finally:
                net.listeners.remove(stamps)
            return steady(stamps.t)

        run = [0]

        def t_elastic():
            run[0] += 1
            stamps = _Stamps()
            net.listeners.append(stamps)
            membership = FileMembership(
                os.path.join(work_dir, f"members-{run[0]}"), process_id=0,
                world_size=1, heartbeat_interval=0.1, log_fn=None)
            trainer = ElasticTrainer(
                net, os.path.join(work_dir, f"ck-{run[0]}"),
                checkpoint_every=max(1, steps // 3),  # async commits inside
                membership=membership, monitor=monitor, log_fn=None)
            try:
                trainer.fit(it(), epochs=net.epoch + 1)
            finally:
                net.listeners.remove(stamps)
            assert trainer.state == "completed", trainer.state
            return steady(stamps.t)

        def one_ratio():
            return t_elastic() / t_plain()

        ratio, noise = _med3(one_ratio)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    return {
        "metric": "elastic_overhead",
        "model": (f"LeNet-5 B={batch} x{steps} steps under ElasticTrainer "
                  "(100ms heartbeats + async checkpoint every "
                  f"{max(1, steps // 3)} steps + rollback monitor + drain "
                  "handler) vs bare fit()"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x unsupervised step time (1.0 = free)",
        # one-time blocking rollback-target commit (startup, not per-step)
        "checkpoint_seconds": round(checkpoint_seconds, 3),
        # <= 1.0 means the <= 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }


_RECOMPILE_CHILD = r"""
import json, sys, time
T0 = time.perf_counter()   # process-start reference for cold-start wall
import jax
jax.config.update("jax_platforms", "cpu")
cache_dir = sys.argv[1] if len(sys.argv) > 1 and sys.argv[1] != "-" else None
if cache_dir:
    from deeplearning4j_tpu.util.compile_cache import enable_persistent_cache
    enable_persistent_cache(cache_dir)
import numpy as np
from deeplearning4j_tpu.util import get_watcher

w = get_watcher()   # install monitoring hooks BEFORE any compile happens
from deeplearning4j_tpu.zoo import ResNet50

# flagship topology, CPU-sized (the scaling child's config: same graph and
# collective structure as 224px, small enough for the 1-core host)
net = ResNet50(num_classes=16, input_shape=(32, 32, 3)).init()
if cache_dir:
    # full compile-once chain: the AOT lowering store (skips the warm
    # process's Python trace + MLIR build) on top of the persistent cache
    # (skips its backend compile) — docs/COMPILE_CACHE.md
    import os
    net.warmup(shapes=[(8, 32, 32, 3)], inference=False,
               export_dir=os.path.join(cache_dir, "aot"))
rng = np.random.default_rng(0)
x = jax.device_put(rng.normal(size=(8, 32, 32, 3)).astype(np.float32))
y = jax.device_put(np.eye(16, dtype=np.float32)[rng.integers(0, 16, 8)])
step_walls = []
t_first_done = None
for _ in range(6):
    t0 = time.perf_counter()
    net._fit_batch(x, y)
    float(net.score_value)   # completion fence per step (wall attribution)
    step_walls.append(time.perf_counter() - t0)
    if t_first_done is None:
        t_first_done = time.perf_counter()
# first stable step: first index whose wall is within 2x the best tail step
floor = min(step_walls[1:])
stable_at = next(i for i, t in enumerate(step_walls) if t <= 2 * floor)
print(json.dumps({
    "cold_start_s": round(t_first_done - T0, 3),  # launch -> first step done
    "first_step_s": round(step_walls[0], 3),
    "steady_step_s": round(floor, 4),
    "steps_to_stable": stable_at,
    "backend_compiles": w.backend_compiles,
    "persistent_cache_hits": w.persistent_cache_hits,
}))
"""


def bench_recompile_overhead(runs: int = 3):
    """recompile_overhead: warm-persistent-cache cold-PROCESS start over the
    uncached cold start, on the flagship-topology CPU-sized model (ResNet-50
    32px — the scaling child's config). Each sample spawns two child
    processes against one fresh ``compilation_cache_dir``: the first pays
    every XLA compile (and populates the cache), the second deserializes.
    Cold start = process launch to first completed train step. Target:
    warm/cold <= 0.5 (BASELINE.md); median-of-{runs} with the standard
    ``noise`` field. Also reports the ragged-tail compile-count A/B (0 extra
    traces bucketed vs >= 1 unbucketed) measured in-process."""
    import shutil
    import tempfile

    def child(cache_dir):
        # scrub inherited DL4J_TPU_* knobs: an ambient compile-cache or
        # bucketing env var would corrupt the cold/uncached baseline
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("DL4J_TPU_")}
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", _RECOMPILE_CHILD, cache_dir or "-"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = [l for l in out.stdout.strip().splitlines()
                if l.startswith("{")][-1]
        return json.loads(line)

    pairs = []

    def one_ratio():
        td = tempfile.mkdtemp(prefix="dl4j_cc_bench_")
        try:
            cold = child(td)   # empty dir: every compile is real + persisted
            warm = child(td)   # same dir, fresh process: deserialize
        finally:
            shutil.rmtree(td, ignore_errors=True)
        r = warm["cold_start_s"] / cold["cold_start_s"]
        pairs.append((r, cold, warm))
        return r

    ratio, noise = _med3(one_ratio, runs=runs)
    # every reported companion figure comes from the MEDIAN-ratio sample —
    # not run order — so the record is one internally consistent run
    _, cold_med_run, warm_med_run = sorted(
        pairs, key=lambda p: p[0])[len(pairs) // 2]
    cold_med = cold_med_run["cold_start_s"]
    warm_med = warm_med_run["cold_start_s"]
    bucketed, unbucketed = _ragged_tail_traces()
    return {
        "metric": "recompile_overhead",
        "model": ("zoo.ResNet50 32px classes=16 B=8 fp32 (flagship topology,"
                  " CPU-sized); persistent XLA cache + AOT lowering store,"
                  " cold vs warm process"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x uncached cold-process start (lower is better)",
        "cold_start_s": cold_med,
        "warm_start_s": warm_med,
        "warm_cache_hits": warm_med_run["persistent_cache_hits"],
        "steps_to_stable_cold": cold_med_run["steps_to_stable"],
        # ragged-tail epoch (N % B != 0): extra train-step traces beyond the
        # first — 0 under bucketing, >= 1 without (compile_cache_sweep.py
        # demonstrates the same on full epochs)
        "ragged_extra_traces_bucketed": bucketed,
        "ragged_extra_traces_unbucketed": unbucketed,
        # <= 1.0 means the <= 0.5x warm-start target is met (BASELINE.md)
        "vs_baseline": round(ratio / 0.5, 4),
    }


def _ragged_tail_traces():
    """(bucketed, unbucketed) EXTRA train-step traces for a ragged-tail
    epoch (beyond the one expected full-batch compile)."""
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import get_watcher

    def run(buckets):
        # explicit on both axes so an ambient DL4J_TPU_BUCKETS can never
        # bucket the "unbucketed" baseline of this A/B
        b = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
             .batch_buckets(buckets).seq_buckets(None))
        conf = (b.list()
                .layer(DenseLayer(n_in=16, n_out=32, activation="relu"))
                .layer(OutputLayer(n_in=32, n_out=10))
                .set_input_type(InputType.feed_forward(16)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(20, 16)).astype(np.float32)  # 20 % 8 = 4 ragged
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 20)]
        w = get_watcher()
        with w.scope() as s:
            net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            return s.traces_of("MultiLayerNetwork.train_step") - 1
    return run((8,)), run(None)


def bench_serving(classify_requests: int = 48, generate_requests: int = 4,
                  max_new_tokens: int = 6):
    """serving_p99_latency_ms + serving_qps: the serving tier end-to-end at
    the scheduler level (benchmarks/serving_smoke.py covers the HTTP hop;
    gating below HTTP keeps socket scheduling noise out of the bands).
    Mixed two-model multi-tenant workload (docs/SERVING.md): LeNet classify
    requests on the interactive lane of one model + BERT-tiny KV-cache
    decode requests on the batch lane of ANOTHER model, each with its own
    scheduler. All bucket executables are warmed before the timed region
    and the record carries the steady-state ``serving.recompiles_total``
    delta (must be 0) plus a batched-vs-sequential bit-identity probe —
    the ISSUE 8 acceptance facts ride in the BENCH record itself.
    p99 is the exact quantile over every request's submit→complete latency;
    QPS is completed requests over the wall time to full drain. Both
    median-of-3 with the standard noise field."""
    import threading

    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving import ModelRouter, ServingModel
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.zoo.bert import Bert

    lenet = _build_lenet()
    clf = ServingModel(lenet, "lenet", bucketing=BucketingPolicy(
        batch_buckets=(1, 2, 4, 8)))
    bert = Bert.tiny(causal=True, task="mlm", vocab_size=64, max_length=32,
                     hidden_dropout=0.0).init()
    gen = ServingModel(bert, "bert-tiny-decode", kind="generate",
                       bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                                 seq_buckets=(8,)))
    router = ModelRouter(name="bench")
    router.register(clf, max_wait_ms=1.0, queue_limit=256)
    router.register(gen, max_wait_ms=1.0, queue_limit=256)
    router.warmup()

    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    prompts = [list(rng.integers(1, 64, size=5)) for _ in range(4)]

    def one_run():
        lat, lock = [], threading.Lock()
        t_end = [0.0]

        def cb(ts):
            def _done(f):
                now = time.perf_counter()
                with lock:
                    lat.append(now - ts)
                    t_end[0] = max(t_end[0], now)
            return _done

        t0 = time.perf_counter()
        futs = []
        for i in range(generate_requests):
            ts = time.perf_counter()
            f = router.submit("bert-tiny-decode",
                              np.asarray(prompts[i % len(prompts)],
                                         np.int32),
                              lane="batch", max_new_tokens=max_new_tokens)
            f.add_done_callback(cb(ts))
            futs.append(f)
        for i in range(classify_requests):
            ts = time.perf_counter()
            f = router.submit("lenet", images[i % 8][None],
                              lane="interactive")
            f.add_done_callback(cb(ts))
            futs.append(f)
        for f in futs:
            f.result(timeout=300)
        # result() can wake before the done-callbacks have stamped (Future
        # notifies waiters, then invokes callbacks) — wait for every stamp
        # so p99/QPS cover the full sample set
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            with lock:
                if len(lat) == len(futs):
                    break
            time.sleep(1e-3)
        wall = t_end[0] - t0
        lat.sort()
        p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))]
        return p99 * 1e3, len(lat) / wall

    one_run()  # steady-state entry: every signature warm before measuring
    tele = tm.get_telemetry()
    rec_key = lambda: sum(  # noqa: E731
        v for (name, _l), v in tele.counters.items()
        if name == "serving.recompiles_total")
    rec_before = rec_key()
    runs = sorted(one_run() for _ in range(3))
    steady_recompiles = rec_key() - rec_before
    p99s = sorted(r[0] for r in runs)
    qpss = sorted(r[1] for r in runs)
    p99, qps = p99s[1], qpss[1]
    p99_noise = (p99s[-1] - p99s[0]) / 2.0 / p99 if p99 else 0.0
    qps_noise = (qpss[-1] - qpss[0]) / 2.0 / qps if qps else 0.0
    # batched-vs-sequential bit-identity probes (the r8 bucketing contract
    # carried into serving; conv topologies reassociate at ulp across batch
    # shapes on XLA:CPU — the documented docs/COMPILE_CACHE.md exception —
    # so the conv probe compares the same bucket shape, the decode probe is
    # end-to-end exact):
    # 1. classify: scheduler result == direct forward at the same bucket
    pad = np.concatenate([images[:3], np.zeros((1, 28, 28, 1), np.float32)])
    direct = np.asarray(lenet.output(pad))[:3]
    via = router.submit("lenet", images[:3], lane="interactive"
                        ).result(timeout=60)
    # 2. decode: coalesced 2-prompt batch == each prompt generated alone
    both, _ = gen.execute([np.asarray(p, np.int32) for p in prompts[:2]],
                          max_new_tokens=max_new_tokens)
    solo = [gen.execute([np.asarray(p, np.int32)],
                        max_new_tokens=max_new_tokens)[0][0]
            for p in prompts[:2]]
    bit_identical = bool(np.array_equal(np.asarray(via), direct)) \
        and list(both) == list(solo)
    router.shutdown()
    model_desc = (f"LeNet classify x{classify_requests} (interactive lane) "
                  f"+ Bert.tiny causal-mlm KV-decode x{generate_requests} "
                  f"({max_new_tokens} new tokens, batch lane), per-model "
                  "schedulers, scheduler-level round trip")
    return [{
        "metric": "serving_p99_latency_ms",
        "model": model_desc,
        "value": round(p99, 2),
        "noise": f"±{round(100 * p99_noise, 1)}% (3-sample spread/2)",
        "unit": "ms (submit -> complete, p99 over all requests)",
        "steady_recompiles": int(steady_recompiles),  # must be 0
        "batched_bit_identical": bit_identical,       # must be True
        "vs_baseline": None,  # first number on this axis
    }, {
        "metric": "serving_qps",
        "model": model_desc,
        "value": round(qps, 2),
        "noise": f"±{round(100 * qps_noise, 1)}% (3-sample spread/2)",
        "unit": "completed requests/sec (mixed workload, to drain)",
        "vs_baseline": None,  # first number on this axis
    }]


def bench_request_tracing_overhead(classify_requests: int = 144,
                                   generate_requests: int = 6,
                                   max_new_tokens: int = 8):
    """request_tracing_overhead: the r13 mixed two-model serving workload's
    wall time with request tracing FULLY ON (DL4J_TPU_TRACE_SAMPLE=1 —
    every request emits queue/fill/compute phase spans, batch pad/device
    spans, per-token decode spans, and a flight-recorder record) over the
    identical workload with tracing OFF (=0 — timestamps still stamped,
    nothing emitted). Sampling at 100% is the WORST case; the default 2%
    head sample costs a fraction of this. Target ≤ 1.05x, the r9
    telemetry_overhead convention (docs/OBSERVABILITY.md). Median-of-3 of
    the ratio with the standard noise field."""
    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving import ModelRouter, ServingModel
    from deeplearning4j_tpu.zoo.bert import Bert

    lenet = _build_lenet()
    clf = ServingModel(lenet, "lenet-tr", bucketing=BucketingPolicy(
        batch_buckets=(1, 2, 4, 8)))
    bert = Bert.tiny(causal=True, task="mlm", vocab_size=64, max_length=32,
                     hidden_dropout=0.0).init()
    gen = ServingModel(bert, "bert-tr-decode", kind="generate",
                       bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                                 seq_buckets=(8,)))
    router = ModelRouter(name="tracing-bench")
    router.register(clf, max_wait_ms=1.0, queue_limit=256)
    router.register(gen, max_wait_ms=1.0, queue_limit=256)
    router.warmup()

    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    prompts = [list(rng.integers(1, 64, size=5)) for _ in range(4)]

    def one_run() -> float:
        t0 = time.perf_counter()
        futs = []
        for i in range(generate_requests):
            futs.append(router.submit(
                "bert-tr-decode",
                np.asarray(prompts[i % len(prompts)], np.int32),
                lane="batch", max_new_tokens=max_new_tokens))
        for i in range(classify_requests):
            futs.append(router.submit("lenet-tr", images[i % 8][None],
                                      lane="interactive"))
        for f in futs:
            f.result(timeout=300)
        return time.perf_counter() - t0

    saved = os.environ.get("DL4J_TPU_TRACE_SAMPLE")

    def timed(sample: str) -> float:
        os.environ["DL4J_TPU_TRACE_SAMPLE"] = sample
        one_run()  # settle at this sampling mode
        return one_run()

    try:
        # counterbalanced A/B: alternate which mode is timed first — a
        # sequential off-then-on pair reads monotone machine drift as
        # tracing overhead (measured: the same workload A/B'd per-mode
        # back-to-back shows ≈0 cost, while off→on ordering showed a
        # phantom ~5%)
        order = itertools.cycle([("0", "1"), ("1", "0")])

        def one_ratio():
            first, second = next(order)
            t = {first: timed(first), second: timed(second)}
            return t["1"] / t["0"]

        ratio, noise = _med3(one_ratio)
    finally:
        if saved is None:
            os.environ.pop("DL4J_TPU_TRACE_SAMPLE", None)
        else:
            os.environ["DL4J_TPU_TRACE_SAMPLE"] = saved
        router.shutdown()
    return {
        "metric": "request_tracing_overhead",
        "model": (f"LeNet classify x{classify_requests} + Bert.tiny "
                  f"KV-decode x{generate_requests} ({max_new_tokens} new "
                  "tokens), scheduler round trip, DL4J_TPU_TRACE_SAMPLE=1 "
                  "(every request traced) vs 0"),
        "value": round(ratio, 4),
        "noise": noise,
        "unit": "x untraced serving wall time (1.0 = free)",
        # ≤ 1.0 means the ≤ 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }


def bench_serving_resilience(classify_requests: int = 96,
                             generate_requests: int = 4,
                             max_new_tokens: int = 6,
                             storm_reloads: int = 3):
    """serving_resilience_overhead + serving_reload_p99_delta_ms (ISSUE 13,
    docs/SERVING.md#resilience).

    Overhead: the r13 mixed two-model workload on a router with the full
    resilience layer armed (supervised watchdog wrapping the worker loop,
    per-model circuit breaker gating every submit and recording every batch
    outcome) over an identical router with both OFF (``breaker=None,
    supervised=False``). Target ≤ 1.05x, the r9 telemetry_overhead
    convention. Counterbalanced A/B (which router is timed first alternates
    per median sample — the r17 lesson: sequential ordering reads monotone
    machine drift as phantom overhead), median-of-3 of the ratio.

    Reload delta: p99 submit→complete latency of the same traffic WHILE a
    rolling-reload storm runs (``storm_reloads`` back-to-back
    ``ModelRouter.reload`` calls — restore + shadow warmup + canary + swap
    on the caller's thread) minus p99 over a steady window of the same
    duration. The contract is zero shed and zero steady-state recompiles
    (both carried in the record); the delta is what the storm's CPU theft
    (shadow warmup compiles XLA programs) costs the p99 tail. Floored at
    0.5 ms: a storm measurably CHEAPER than steady state is timer noise,
    and the floor keeps the LOWER_BETTER gate band multiplicative. On this
    CPU container the shadow compiles contend for the same cores that
    serve — on a real TPU host the compile is host-side while serving is
    device-side, so this number is an upper bound (the r6 convention: CPU
    proves the contract, cannot rank the cost)."""
    import threading

    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving import ModelRouter, ServingModel
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    from deeplearning4j_tpu.zoo.bert import Bert

    def build_router(tag: str, **sched_kw):
        lenet = _build_lenet()
        clf = ServingModel(lenet, f"lenet-{tag}",
                           bucketing=BucketingPolicy(
                               batch_buckets=(1, 2, 4, 8)))
        bert = Bert.tiny(causal=True, task="mlm", vocab_size=64,
                         max_length=32, hidden_dropout=0.0).init()
        gen = ServingModel(bert, f"bert-{tag}-decode", kind="generate",
                           bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                                     seq_buckets=(8,)))
        router = ModelRouter(name=f"resilience-bench-{tag}")
        router.register(clf, max_wait_ms=1.0, queue_limit=512, **sched_kw)
        router.register(gen, max_wait_ms=1.0, queue_limit=512, **sched_kw)
        router.warmup()
        return router

    # the A/B pair: the full layer armed vs both legs off
    on_router = build_router("rs")
    off_router = build_router("rs0", breaker=None, supervised=False)

    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    prompts = [list(rng.integers(1, 64, size=5)) for _ in range(4)]

    def one_run(router, tag: str) -> float:
        t0 = time.perf_counter()
        futs = []
        for i in range(generate_requests):
            futs.append(router.submit(
                f"bert-{tag}-decode",
                np.asarray(prompts[i % len(prompts)], np.int32),
                lane="batch", max_new_tokens=max_new_tokens))
        for i in range(classify_requests):
            futs.append(router.submit(f"lenet-{tag}", images[i % 8][None],
                                      lane="interactive"))
        for f in futs:
            f.result(timeout=300)
        return time.perf_counter() - t0

    def timed(which: str) -> float:
        router, tag = ((on_router, "rs") if which == "on"
                       else (off_router, "rs0"))
        one_run(router, tag)  # settle
        return one_run(router, tag)

    order = itertools.cycle([("on", "off"), ("off", "on")])

    def one_ratio():
        first, second = next(order)
        t = {first: timed(first), second: timed(second)}
        return t["on"] / t["off"]

    ratio, ratio_noise = _med3(one_ratio)

    # -------- reload storm p99 delta (on_router; the off one is done)
    off_router.shutdown()
    tmpdir = tempfile.mkdtemp(prefix="bench-reload-")
    try:
        paths = []
        for i in range(storm_reloads):
            p = os.path.join(tmpdir, f"v{i}.zip")
            ModelSerializer.write_model(_build_lenet(seed=i + 1), p,
                                        save_updater=False)
            paths.append(p)

        def traffic(stop, lat, errs):
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    on_router.submit("lenet-rs",
                                     images[0][None],
                                     lane="interactive").result(timeout=120)
                    lat.append(time.perf_counter() - t0)
                except Exception as e:  # noqa: BLE001 — zero-shed contract
                    errs.append(repr(e))

        def p99_window(storm: bool, duration: float):
            stop, lat, errs = threading.Event(), [], []
            threads = [threading.Thread(target=traffic,
                                        args=(stop, lat, errs))
                       for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.1)
            t0 = time.perf_counter()
            if storm:
                for p in paths:
                    on_router.reload("lenet-rs", p)
            else:
                time.sleep(duration)
            wall = time.perf_counter() - t0
            time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            if not lat:
                # every request in the window failed: surface the REAL
                # diagnosis (the zero-shed contract broke) instead of an
                # IndexError from indexing an empty quantile list
                raise RuntimeError(
                    f"reload-delta window completed 0 requests "
                    f"({len(errs)} errors; first: {errs[:1]})")
            lat.sort()
            p99 = lat[min(len(lat) - 1,
                          int(round(0.99 * (len(lat) - 1))))] * 1e3
            return p99, wall, len(errs), len(lat)

        tele = tm.get_telemetry()
        rec = lambda: tele.counter_total(  # noqa: E731
            "serving.recompiles_total", model="lenet-rs")
        storm_wall = None
        shed = 0
        n_requests = 0
        rec0 = rec()

        def one_delta():
            nonlocal storm_wall, shed, n_requests
            # storm first so the steady window can duration-match it; the
            # traffic loop itself is identical on both sides
            p99_storm, storm_wall, e1, n1 = p99_window(True, 0.0)
            p99_steady, _w, e2, n2 = p99_window(False, storm_wall)
            shed += e1 + e2
            n_requests += n1 + n2
            return p99_storm - p99_steady

        vals = sorted(one_delta() for _ in range(3))
        delta = vals[1]
        # noise over the FLOORED value: a near-zero delta's spread divided
        # by itself would explode (or flip sign), and the floor is what the
        # gate band is built on
        delta_noise = (f"±{round(100 * (vals[-1] - vals[0]) / 2.0 / max(delta, 0.5), 1)}"
                       "% (3-sample spread/2 over the floored value)")
        steady_recompiles = rec() - rec0
        reload_version = on_router.get("lenet-rs")[0].version
    finally:
        on_router.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)

    model_desc = (f"LeNet classify x{classify_requests} (interactive) + "
                  f"Bert.tiny KV-decode x{generate_requests} "
                  f"({max_new_tokens} new tokens, batch lane), per-model "
                  "schedulers")
    return [{
        "metric": "serving_resilience_overhead",
        "model": (model_desc + "; supervised watchdog + circuit breaker ON "
                  "vs breaker=None, supervised=False (counterbalanced A/B)"),
        "value": round(ratio, 4),
        "noise": ratio_noise,
        "unit": "x unguarded serving wall time (1.0 = free)",
        # ≤ 1.0 means the ≤ 1.05x overhead target is met
        "vs_baseline": round(ratio / 1.05, 4),
    }, {
        "metric": "serving_reload_p99_delta_ms",
        "model": (f"LeNet classify closed-loop x3 threads; p99 during a "
                  f"{storm_reloads}-reload rolling storm (restore + shadow "
                  "warmup + canary + swap) minus duration-matched steady "
                  "p99; floored at 0.5 ms; CPU container — shadow compiles "
                  "contend with serving cores, an upper bound vs a real "
                  "TPU host"),
        "value": round(max(delta, 0.5), 2),
        "raw_delta_ms": round(delta, 2),
        "noise": delta_noise,
        "unit": "ms added to p99 by a reload storm (0.5 = floor)",
        "storm_reloads": storm_reloads * 3,       # 3 samples x storm
        "storm_shed": shed,                       # must be 0
        "storm_requests": n_requests,
        "steady_recompiles": int(steady_recompiles),  # must be 0
        "reload_version": int(reload_version),
        "vs_baseline": None,  # first number on this axis
    }]


def bench_decode_paged(streams: int = 32, prompt_len: int = 16,
                       max_new: int = 8):
    """concurrent_streams_per_device (ISSUE 15 headline, HIGHER_BETTER):
    how many decode streams ONE device's KV bytes hold under the paged
    block pool vs the r13 contiguous layout. Deterministic byte accounting
    of the placement (the r10/r19 convention — a regression means the
    pool stopped paging, not that a timer wobbled): the pool is sized to
    the contiguous ceiling's exact byte budget (64 blocks × 16 slots =
    1024 token slots = 8 streams × max_length 128), then a REAL mixed
    batch of 32 typical-length streams (prompt 16 + 8 new = 24 tokens →
    2 blocks each) is admitted and decoded through it — 4× the streams in
    the same bytes, measured from the pool's high-water mark, not
    computed."""
    from deeplearning4j_tpu.serving.generate import Generator
    from deeplearning4j_tpu.zoo.bert import Bert

    net = Bert.tiny(causal=True, task="mlm", vocab_size=64, max_length=128,
                    hidden_dropout=0.0).init()
    gen = Generator(net, paged=True, block_size=16, pool_blocks=64,
                    batch_buckets=(1, 2, 4, 8, 16, 32),
                    prefill_buckets=(16,))
    pool = gen.pool
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(1, 64, size=prompt_len)))
               for _ in range(streams)]
    out = gen.generate(prompts, max_new_tokens=max_new)
    assert all(len(r) == max_new for r in out)
    assert pool.free_blocks() == pool.num_blocks  # all freed
    ceiling = pool.contiguous_stream_ceiling()
    peak = pool.peak_streams
    return {
        "metric": "concurrent_streams_per_device",
        "model": (f"BERT-tiny causal decoder, paged KV pool "
                  f"{pool.num_blocks}x{pool.block_size} slots = "
                  f"{pool.pool_bytes()} B (the contiguous layout's exact "
                  f"budget for {ceiling} streams @ max_length "
                  f"{gen.max_length}); {streams} real streams of "
                  f"{prompt_len}+{max_new} tokens admitted and decoded — "
                  f"deterministic byte accounting of the placement, "
                  f"measured at the pool high-water mark"),
        "value": int(peak),
        "noise": "±0.0% (deterministic block accounting)",
        "unit": "streams/device",
        "vs_baseline": round(peak / ceiling, 4),  # vs contiguous ceiling
    }


def bench_speculative_decode(batch: int = 4, prompt_len: int = 8,
                             max_new: int = 24):
    """speculative_decode_tokens_per_sec vs the non-speculative paged
    baseline (honest CPU A/B per the r6/r15 convention): greedy decode of
    the same prompts through (a) the plain per-token paged loop and
    (b) the speculative path with a random-init Bert.draft — on CPU the
    draft accepts ~nothing, so every round pays draft steps + a verify
    window to emit ~1 token and speculation LOSES; the committed value
    pins today's spec-path throughput so the machinery can't silently
    regress, while the note carries the perfect-draft ceiling (the
    window-amortization upper bound a distilled draft approaches). CPU
    cannot rank the win — acceptance rates on real traffic ride the
    per-request ``draft_accept_rate`` ruler (docs/OBSERVABILITY.md)."""
    from deeplearning4j_tpu.serving.generate import Generator
    from deeplearning4j_tpu.zoo.bert import Bert

    net = Bert.tiny(causal=True, task="mlm", vocab_size=64, max_length=64,
                    hidden_dropout=0.0).init()
    draft = Bert.draft(vocab_size=64, max_length=64).init()
    buckets = dict(batch_buckets=(1, 2, 4), prefill_buckets=(8,))
    g_plain = Generator(net, paged=True, block_size=16, **buckets)
    g_spec = Generator(net, paged=True, block_size=16, draft_net=draft,
                       spec_tokens=4, **buckets)
    g_self = Generator(net, paged=True, block_size=16, draft_net=net,
                       spec_tokens=4, **buckets)
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(1, 64, size=prompt_len)))
               for _ in range(batch)]
    for g in (g_plain, g_spec, g_self):
        g.warmup()
        g.generate(prompts, max_new_tokens=max_new)  # warm the whole loop
    want = g_plain.generate(prompts, max_new_tokens=max_new)
    assert g_spec.generate(prompts, max_new_tokens=max_new) == want
    assert g_self.generate(prompts, max_new_tokens=max_new) == want

    def tps(g, stats=None):
        def run():
            t0 = time.perf_counter()
            out = g.generate(prompts, max_new_tokens=max_new, stats=stats)
            dt = time.perf_counter() - t0
            return sum(len(r) for r in out) / dt
        return _med3(run)

    base, base_noise = tps(g_plain)
    st = {}
    spec, spec_noise = tps(g_spec, stats=st)
    ceiling, _ = tps(g_self)
    return {
        "metric": "speculative_decode_tokens_per_sec",
        "model": (f"BERT-tiny target + Bert.draft (1L/64H random-init, "
                  f"accept {st.get('spec_accept_rate', 0):.3f}) greedy "
                  f"B={batch} T+{max_new}; honest CPU A/B: plain paged "
                  f"{base:.1f} tok/s {base_noise}, speculative "
                  f"{spec:.1f} tok/s, perfect-draft ceiling "
                  f"{ceiling:.1f} tok/s (window amortization at accept "
                  f"1.0) — CPU cannot rank the win, a distilled draft + "
                  f"chip verify economics decide it; token identity "
                  f"asserted in-run"),
        "value": round(spec, 2),
        "noise": spec_noise,
        "unit": "tokens/sec",
        "vs_baseline": round(spec / base, 4),  # vs non-speculative
    }


def bench_prefix_decode(streams: int = 64, system_len: int = 56,
                        suffix_len: int = 4, max_new: int = 4):
    """concurrent_streams_per_device on PREFIX-HEAVY traffic (ISSUE 16
    headline, HIGHER_BETTER) plus prefix_cache_ttft_speedup. Deterministic
    byte accounting in the SAME usable byte budget as the r11 record
    (1024 token slots = the contiguous layout's 8 streams @ max_length
    128; here 128 blocks × 8 slots): with a 56-token system prompt
    resident ONCE in the radix cache (7 shared blocks), 64 streams of
    64-token context (56 shared + 4 unique suffix + 4 generated) each
    admit ONE fresh block — 7 + 64 = 71 of 128 blocks — where the
    unshared paged layout would need 8 blocks/stream (512 total) and the
    r11 mixed batch held 32 streams of 24-token context. Identity vs an
    uncached paged reference is asserted in-run. The TTFT companion is a
    wall-clock A/B on this host: first-token latency for a warm-cache
    batch (prefill resumes at position 56, an 8-wide window) vs the same
    batch cold (full 64-wide prefill), median of 3."""
    from deeplearning4j_tpu.serving.generate import Generator
    from deeplearning4j_tpu.zoo.bert import Bert

    net = Bert.tiny(causal=True, task="mlm", vocab_size=64, max_length=128,
                    hidden_dropout=0.0).init()
    buckets = dict(batch_buckets=(1, 8, streams), prefill_buckets=(8, 64))
    gen = Generator(net, paged=True, block_size=8, pool_blocks=128,
                    prefix_cache=True, **buckets)
    ref = Generator(net, paged=True, block_size=8, pool_blocks=600,
                    **buckets)
    pool = gen.pool
    rng = np.random.default_rng(0)
    system = list(map(int, rng.integers(1, 64, size=system_len)))
    prompts = [system + list(map(int, rng.integers(1, 64, size=suffix_len)))
               for _ in range(streams)]
    # resident system prompt: one prior request commits the shared blocks
    gen.generate([prompts[0]], max_new_tokens=max_new)
    out = gen.generate(prompts, max_new_tokens=max_new)
    assert out == ref.generate(prompts, max_new_tokens=max_new)
    ok, detail = pool.conservation()
    assert ok, detail
    ceiling = pool.contiguous_stream_ceiling()
    peak = pool.peak_streams
    shared_blocks = system_len // pool.block_size
    headline = {
        "metric": "concurrent_streams_per_device",
        "model": (f"BERT-tiny causal decoder, prefix-heavy traffic: paged "
                  f"KV pool {pool.num_blocks}x{pool.block_size} slots = "
                  f"{pool.pool_bytes()} B (the r11 budget: contiguous "
                  f"ceiling {ceiling} streams @ max_length "
                  f"{gen.max_length}); {streams} streams of "
                  f"{system_len}+{suffix_len}+{max_new}-token context "
                  f"sharing the {system_len}-token system prompt via the "
                  f"radix cache ({shared_blocks} resident blocks, 1 fresh "
                  f"block/stream) — deterministic block accounting at the "
                  f"pool high-water mark, token identity vs the uncached "
                  f"paged reference asserted in-run"),
        "value": int(peak),
        "noise": "±0.0% (deterministic block accounting)",
        "unit": "streams/device",
        "vs_baseline": round(peak / ceiling, 4),  # vs contiguous ceiling
    }

    # --- TTFT A/B: warm radix cache vs cold, same batch, max_new=1
    ttft_prompts = prompts[:8]
    gen.warmup()

    def cold_once():
        gen.cache.flush()
        t0 = time.perf_counter()
        gen.generate(ttft_prompts, max_new_tokens=1)
        return time.perf_counter() - t0

    cold_once()  # trace anything warmup missed before timing
    cold_s, cold_noise = _med3(cold_once)
    gen.generate(ttft_prompts, max_new_tokens=1)  # prime the trie

    def warm_once():
        t0 = time.perf_counter()
        gen.generate(ttft_prompts, max_new_tokens=1)
        return time.perf_counter() - t0

    warm_s, warm_noise = _med3(warm_once)
    ttft = {
        "metric": "prefix_cache_ttft_speedup",
        "model": (f"same decoder/pool: first-token latency for a warm "
                  f"{len(ttft_prompts)}-stream batch (prefill resumes at "
                  f"position {system_len}, 8-wide window, "
                  f"{warm_s * 1e3:.1f} ms {warm_noise}) vs cold "
                  f"({cold_s * 1e3:.1f} ms {cold_noise}, full 64-wide "
                  f"prefill), this host"),
        "value": round(cold_s / warm_s, 4),
        "noise": warm_noise,
        "unit": "x",
        "vs_baseline": round(cold_s / warm_s, 4),  # vs cold prefill
    }
    return [headline, ttft]


def bench_fleet(n_big: int = 4, window_s: float = 4.0, clients: int = 12):
    """fleet_qps_scaling_efficiency (ISSUE 18 headline, HIGHER_BETTER,
    gated) + fleet_routing_overhead_ms (LOWER_BETTER). A FleetRouter over
    real worker processes serving a compute-weighted dense classifier
    (128->1024->1024->8; each worker pinned single-threaded via
    XLA_FLAGS=--xla_cpu_multi_thread_eigen=false + OMP_NUM_THREADS=1 so
    worker count, not intra-op threading, is the parallelism axis).

    Efficiency = QPS(N=4) / (min(N, host_cores) x QPS(N=1)) — normalized
    by EFFECTIVE parallelism, the honest-CPU rule: on this 1-core
    container 4 single-threaded workers cannot exceed one core's
    throughput, so the raw N x QPS(1) denominator would measure the host,
    not the fleet (the dp_sharding_efficiency precedent,
    HOST_CONDITION_FLOOR in regression_gate.py). At saturation the metric
    becomes the disaggregation tax: what routing + 4-way process
    multiplexing retain of one worker's direct throughput. On a >=5-core
    host the SAME expression measures true QPS scaling.

    The overhead companion is p50(serial request through a 1-worker
    fleet) - p50(same request direct to that worker): the per-hop cost of
    the routing tier (rendezvous hash + header relay + pooled proxy
    connection), in ms."""
    import http.client
    import threading

    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving.fleet import FleetRouter, fleet_spec
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .batch_buckets((1, 2, 4, 8)).list()
            .layer(DenseLayer(n_in=128, n_out=1024, activation="relu"))
            .layer(DenseLayer(n_in=1024, n_out=1024, activation="relu"))
            .layer(OutputLayer(n_in=1024, n_out=8, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(128)).build())
    net = MultiLayerNetwork(conf).init()
    tmp = tempfile.mkdtemp(prefix="dl4j_fleet_bench_")
    clf_path = os.path.join(tmp, "clf.zip")
    ModelSerializer.write_model(net, clf_path, save_updater=False)
    spec = fleet_spec(
        models=[{"id": "clf", "path": clf_path, "kind": "classify",
                 "register": {"max_wait_ms": 2.0, "queue_limit": 512}}],
        env={"JAX_PLATFORMS": "cpu", "OMP_NUM_THREADS": "1",
             "XLA_FLAGS": "--xla_cpu_multi_thread_eigen=false"})
    row = np.random.default_rng(0).normal(size=(1, 128)).tolist()
    payload = json.dumps({"inputs": row}).encode()

    def post_one(conn):
        conn.request("POST", "/v1/models/clf/infer", body=payload,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        r.read()
        return r.status

    def qps_window(fleet):
        done = [0] * clients
        t_end = time.perf_counter() + window_s

        def client(i):
            conn = http.client.HTTPConnection("127.0.0.1", fleet.port,
                                              timeout=60)
            try:
                while time.perf_counter() < t_end:
                    if post_one(conn) == 200:
                        done[i] += 1
            finally:
                conn.close()

        t0 = time.perf_counter()
        ts = [threading.Thread(target=client, args=(i,))
              for i in range(clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return sum(done) / (time.perf_counter() - t0)

    def p50_serial(port, n=80):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            lats = []
            for _ in range(n):
                t0 = time.perf_counter()
                post_one(conn)
                lats.append(time.perf_counter() - t0)
        finally:
            conn.close()
        return sorted(lats)[n // 2]

    def boot(n):
        f = FleetRouter(spec, n_workers=n, health_interval_s=0.25,
                        name=f"bench{n}").start()
        p50_serial(f.port, n=16)  # settle conn pools + anything unwarmed
        return f

    f1 = boot(1)
    q1, q1_noise = _med3(lambda: qps_window(f1))
    w_port = f1.workers[0].port
    direct_p50, _dn = _med3(lambda: p50_serial(w_port))
    fleet_p50, fleet_noise = _med3(lambda: p50_serial(f1.port))
    f1.stop()
    f4 = boot(n_big)
    q4, q4_noise = _med3(lambda: qps_window(f4))
    f4.stop()

    cores = os.cpu_count() or 1
    denom = min(n_big, cores)
    eff = q4 / (denom * q1)
    overhead_ms = max(0.0, (fleet_p50 - direct_p50) * 1e3)
    scaling = {
        "metric": "fleet_qps_scaling_efficiency",
        "model": (f"FleetRouter over {n_big} worker processes vs 1, dense "
                  f"128->1024->1024->8 classifier, {clients} persistent "
                  f"HTTP clients x {window_s:.0f}s windows; workers pinned "
                  f"single-threaded (eigen+OMP=1) so worker count is the "
                  f"only parallelism axis. QPS(N={n_big})={q4:.1f} "
                  f"{q4_noise}, QPS(N=1)={q1:.1f} {q1_noise}; efficiency "
                  f"normalized by EFFECTIVE parallelism min(N, host_cores"
                  f"={cores})={denom} — on this 1-core host the metric is "
                  f"the disaggregation tax at core saturation (honest-CPU "
                  f"rule, the dp_sharding precedent); on >=5 cores the "
                  f"same expression is true QPS scaling"),
        "value": round(eff, 4),
        "noise": q4_noise,
        "unit": "fraction",
        "vs_baseline": round(eff, 4),  # vs perfect scaling at 1.0
    }
    routing = {
        "metric": "fleet_routing_overhead_ms",
        "model": (f"p50 of a serial classify request through a 1-worker "
                  f"fleet ({fleet_p50 * 1e3:.2f} ms) minus p50 direct to "
                  f"the worker ({direct_p50 * 1e3:.2f} ms): rendezvous "
                  f"hash + header relay + pooled proxy hop, this host"),
        "value": round(overhead_ms, 3),
        "noise": fleet_noise,
        "unit": "ms",
        "vs_baseline": round(overhead_ms, 3),
    }
    return [scaling, routing]


def main():
    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # Smaller config on CPU so the bench finishes; real sizes on the chip.
    batch = 256 if on_tpu else 8
    image = 224 if on_tpu else 64
    steps = 20 if on_tpu else 3
    try:
        result = bench_resnet50(batch=batch, image=image, steps=steps)
    except Exception as e:  # zoo not built yet / OOM: fall back
        print(f"resnet50 bench unavailable ({type(e).__name__}: {e}); "
              "falling back to LeNet", file=sys.stderr)
        result = bench_lenet(batch=512 if on_tpu else 64, steps=steps)
    extra = []
    try:
        # batch 128: measured sweep (BASELINE.md) — 32 underutilizes the MXU
        # (877 samples/s vs 1,166 at 128); flash attention loses at seq 128
        extra.append(bench_bert(batch=128 if on_tpu else 4,
                                seq=128 if on_tpu else 32,
                                steps=steps, tiny=not on_tpu))
    except Exception as e:
        print(f"bert bench failed: {type(e).__name__}: {e}", file=sys.stderr)
    try:
        extra.append(bench_scaling())
    except Exception as e:
        print(f"scaling bench failed: {type(e).__name__}: {e}", file=sys.stderr)
    try:
        extra.append(bench_zero_memory())
    except Exception as e:
        print(f"zero memory bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_tp_bert_smoke())
    except Exception as e:
        print(f"tp bert smoke failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_compression_ratio())
    except Exception as e:
        print(f"compression ratio bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.extend(bench_pipeline())
    except Exception as e:
        print(f"pipeline bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    if on_tpu:  # flash-vs-naive only means anything on the real chip
        try:
            extra.append(bench_attention_2k())
        except Exception as e:
            print(f"attention bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    try:
        extra.append(bench_lstm_char_rnn(
            batch=128 if on_tpu else 8, seq=128 if on_tpu else 16,
            hidden=512 if on_tpu else 32, steps=60 if on_tpu else 3))
    except Exception as e:
        print(f"lstm bench failed: {type(e).__name__}: {e}", file=sys.stderr)
    try:
        extra.append(bench_host_pipeline(batch=64 if on_tpu else 16,
                                         n_batches=24))
    except Exception as e:
        print(f"host pipeline bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_recompile_overhead())
    except Exception as e:
        print(f"recompile overhead bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # B=64 even on CPU: smaller batches make the step so short that
        # scheduler noise swamps the ~µs-scale span cost being measured
        extra.append(bench_telemetry_overhead(batch=64))
    except Exception as e:
        print(f"telemetry overhead bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_cost_attribution(batch=64))
    except Exception as e:
        print(f"cost attribution bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_optimizer_update_share(batch=64))
    except Exception as e:
        print(f"optimizer update share bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_autotune_dispatch())
    except Exception as e:
        print(f"autotune dispatch bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # B=64 like the other overhead benches: the per-step costs being
        # measured (heartbeat thread wakeups, async-checkpoint enqueue) are
        # fixed, so tiny steps would drown them in scheduler noise
        extra.append(bench_elastic_overhead(batch=64))
    except Exception as e:
        print(f"elastic overhead bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.extend(bench_serving())
    except Exception as e:
        print(f"serving bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_request_tracing_overhead())
    except Exception as e:
        print(f"request tracing overhead bench failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
    try:
        extra.extend(bench_serving_resilience())
    except Exception as e:
        print(f"serving resilience bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_decode_paged())
    except Exception as e:
        print(f"paged decode bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        extra.append(bench_speculative_decode())
    except Exception as e:
        print(f"speculative decode bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # ISSUE 16: prefix-heavy streams-per-device (supersedes the r11
        # mixed-batch measurement of the same metric) + TTFT speedup
        extra.extend(bench_prefix_decode())
    except Exception as e:
        print(f"prefix decode bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # ISSUE 18: disaggregated fleet — QPS scaling efficiency over N
        # real worker processes (normalized by effective host parallelism,
        # see bench_fleet) + the routing tier's per-hop p50 overhead
        extra.extend(bench_fleet())
    except Exception as e:
        print(f"fleet bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    result["extra_metrics"] = extra
    print(json.dumps(result))


if __name__ == "__main__":
    main()
