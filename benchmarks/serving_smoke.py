"""Serving smoke check (the ISSUE 8 CI leg, wired in ci.yml/ci_local.sh).

End-to-end proof of the serving-tier acceptance criteria on a real HTTP
server with two models:

1. boot a :class:`ModelServer` over a router holding a dense classifier
   (explicit batch buckets) and a causal BERT-tiny KV-cache decoder,
   warm every bucket executable, then fire CONCURRENT mixed-model
   requests (interactive classify + batch-lane generate) from worker
   threads through the real HTTP surface;
2. assert every response is correct-shaped, the classify responses are
   BIT-identical to a direct ``net.output`` at the same bucket, p99
   submit→complete latency sits under a CPU sanity bound, and the
   steady-state ``serving.recompiles_total`` delta is exactly 0
   (compile-once serving — docs/SERVING.md);
3. exercise the load-shed contract deterministically: an already-expired
   ``deadline_ms`` answers HTTP 429 with Retry-After, an unknown model
   404;
4. curl ``/metrics`` (Prometheus text with the serving series) and
   ``/healthz`` (JSON with the serving section), then drain gracefully
   and assert a post-drain request answers 503;
5. (ISSUE 12) prove the request-scope layer end-to-end: an inbound
   ``X-Request-Id`` echoes on the response header AND body, the sampled
   trace (``DL4J_TPU_TRACE_SAMPLE=1`` for the whole smoke) carries
   queue-wait/compute spans for that exact id plus per-token decode
   spans for the generate traffic, ``/slo`` serves burn-rate math for a
   declared objective, a synthetic budget-exhausted objective flips
   ``/healthz`` to 503 (and recovery flips it back), and the
   flight-recorder dump at ``/v1/models/<id>/debug/requests`` is
   non-empty after the forced deadline shed with the shed cause on
   record.

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/serving_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []

P99_SANITY_MS = 2500.0  # CPU CI bound: catches collapse, not jitter


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def http_get(url: str, use_curl: bool):
    """(status, body) via curl when available (the CI leg's literal
    requirement), urllib otherwise."""
    if use_curl and shutil.which("curl"):
        out = subprocess.run(
            ["curl", "-sS", "-w", "\n%{http_code}", url],
            capture_output=True, text=True, timeout=30)
        body, _, code = out.stdout.rpartition("\n")
        if not code.strip().isdigit():
            return 0, f"curl failed: {out.stderr.strip()}"
        return int(code), body
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def http_post(url: str, obj: dict):
    """(status, json body, retry_after) for a JSON POST."""
    code, body, headers = http_post_full(url, obj)
    return code, body, headers.get("Retry-After")


def http_post_full(url: str, obj: dict, request_id: str = None):
    """(status, json body, response headers) for a JSON POST, optionally
    carrying an ``X-Request-Id`` (the ISSUE 12 round-trip check)."""
    data = json.dumps(obj).encode()
    headers = {"Content-Type": "application/json"}
    if request_id is not None:
        headers["X-Request-Id"] = request_id
    req = urllib.request.Request(url, data=data, headers=headers)
    try:
        r = urllib.request.urlopen(req, timeout=60)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {}
        return e.code, body, dict(e.headers)


def build_server():
    import numpy as np

    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving import (ModelRouter, ModelServer,
                                            ServingModel)
    from deeplearning4j_tpu.zoo.bert import Bert

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .batch_buckets((1, 2, 4, 8)).list()
            .layer(DenseLayer(n_in=12, n_out=32, activation="relu"))
            .layer(OutputLayer(n_in=32, n_out=5, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(12)).build())
    clf_net = MultiLayerNetwork(conf).init()
    bert = Bert.tiny(causal=True, task="mlm", vocab_size=48, max_length=32,
                     hidden_dropout=0.0).init()
    router = ModelRouter(name="smoke")
    router.register(ServingModel(clf_net, "dense"), max_wait_ms=1.0,
                    queue_limit=128)
    router.register(
        ServingModel(bert, "bert-decode", kind="generate",
                     bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                               seq_buckets=(8,))),
        max_wait_ms=1.0, queue_limit=128)
    server = ModelServer(router, port=0).start()  # warms every bucket
    return server, clf_net, np


def fire_mixed_traffic(server, np, n_classify=24, n_generate=4,
                       threads=4):
    """Concurrent mixed-model HTTP requests; returns per-request latencies
    and the (status, payload) results."""
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(n_classify, 3, 12)).astype(np.float32)
    prompts = [list(map(int, rng.integers(1, 48, size=5)))
               for _ in range(n_generate)]
    jobs = []
    for i in range(n_classify):
        jobs.append(("dense", {"inputs": xs[i].tolist(),
                               "lane": "interactive"}))
    for p in prompts:
        jobs.append(("bert-decode", {"prompt_tokens": [p],
                                     "max_new_tokens": 4, "lane": "batch"}))
    results = [None] * len(jobs)
    lats = [None] * len(jobs)
    idx_lock = threading.Lock()
    next_idx = [0]

    def worker():
        while True:
            with idx_lock:
                if next_idx[0] >= len(jobs):
                    return
                i = next_idx[0]
                next_idx[0] += 1
            model, body = jobs[i]
            t0 = time.perf_counter()
            verb = "generate" if model == "bert-decode" else "infer"
            results[i] = http_post(
                f"{server.url}/v1/models/{model}/{verb}", body)
            lats[i] = time.perf_counter() - t0

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    return jobs, results, lats, xs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--no-curl", action="store_true")
    args = ap.parse_args(argv)
    use_curl = not args.no_curl

    # trace every request for the whole smoke: the span/flight-recorder
    # checks below must not depend on the default 2% head-sample dice
    os.environ["DL4J_TPU_TRACE_SAMPLE"] = "1"
    server, clf_net, np = build_server()
    from deeplearning4j_tpu.util import telemetry as tm

    print("== serving smoke: warm + steady-state traffic ==")
    fire_mixed_traffic(server, np, n_classify=8, n_generate=2)  # settle
    tele = tm.get_telemetry()
    rec = lambda: sum(  # noqa: E731
        v for (name, _l), v in tele.counters.items()
        if name == "serving.recompiles_total")
    rec_before = rec()
    jobs, results, lats, xs = fire_mixed_traffic(server, np)
    ok_all = all(r is not None and r[0] == 200 for r in results)
    check("all mixed-model requests answered 200", ok_all,
          f"{sum(1 for r in results if r and r[0] == 200)}/{len(results)}")
    check("steady-state serving recompiles == 0", rec() - rec_before == 0,
          f"delta {rec() - rec_before}")
    lat_ms = sorted(l * 1e3 for l in lats if l is not None)
    p99 = lat_ms[min(len(lat_ms) - 1, int(round(0.99 * (len(lat_ms) - 1))))]
    check(f"p99 latency under sanity bound ({P99_SANITY_MS:.0f} ms)",
          p99 < P99_SANITY_MS, f"p99 {p99:.1f} ms")

    # classify response bit-identical to a direct forward AT THE SAME
    # BUCKET (3 rows -> bucket 4; docs/SERVING.md bit-identity contract)
    first = results[0][1]["outputs"]
    pad = np.concatenate([xs[0], np.zeros((1, 12), np.float32)])
    direct = np.asarray(clf_net.output(pad))[:3]
    check("classify response bit-identical to direct forward",
          np.array_equal(np.asarray(first, np.float32),
                         direct.astype(np.float32)))

    print("== load-shed contract ==")
    code, _body, retry = http_post(
        f"{server.url}/v1/models/dense/infer",
        {"inputs": xs[0].tolist(), "deadline_ms": -1})
    check("expired deadline answers 429 + Retry-After",
          code == 429 and retry is not None, f"code {code}, retry {retry}")
    code, _body, _ = http_post(f"{server.url}/v1/models/ghost/infer",
                               {"inputs": [[0.0] * 12]})
    check("unknown model answers 404", code == 404, f"code {code}")

    print("== observability surfaces ==")
    code, text = http_get(f"{server.url}/metrics", use_curl)
    check("/metrics answers 200", code == 200)
    for series in ("serving_requests_total", "serving_queue_depth",
                   "serving_recompiles_total",
                   "serving_request_latency_seconds"):
        check(f"/metrics carries {series}", series in text)
    code, text = http_get(f"{server.url}/healthz", use_curl)
    health = json.loads(text) if text.strip().startswith("{") else {}
    check("/healthz answers 200", code == 200)
    models = health.get("serving", {}).get("models", {})
    check("/healthz serving section lists both models",
          set(models) == {"dense", "bert-decode"}, str(sorted(models)))
    check("/healthz reports completed work",
          all(m.get("completed", 0) > 0 for m in models.values()))

    print("== request-scope observability (ISSUE 12) ==")
    # X-Request-Id round-trip: the caller's id comes back on the response
    # header AND body, for 200s and sheds alike
    code, body, hdrs = http_post_full(
        f"{server.url}/v1/models/dense/infer",
        {"inputs": xs[0].tolist()}, request_id="smoke-rid-1")
    check("X-Request-Id echoed on 200 (header + body)",
          code == 200 and hdrs.get("X-Request-Id") == "smoke-rid-1"
          and body.get("request_id") == "smoke-rid-1",
          f"hdr {hdrs.get('X-Request-Id')}, body {body.get('request_id')}")
    code, body, hdrs = http_post_full(
        f"{server.url}/v1/models/dense/infer",
        {"inputs": xs[0].tolist(), "deadline_ms": -1},
        request_id="smoke-rid-shed")
    check("X-Request-Id echoed on the 429 shed",
          code == 429 and hdrs.get("X-Request-Id") == "smoke-rid-shed"
          and body.get("request_id") == "smoke-rid-shed")
    code, body, hdrs = http_post_full(f"{server.url}/v1/models/dense/infer",
                                      {"inputs": xs[0].tolist()})
    check("server mints an id when the caller sends none",
          code == 200 and bool(hdrs.get("X-Request-Id"))
          and body.get("request_id") == hdrs.get("X-Request-Id"))

    # the sampled trace carries the request's phase spans on the shared
    # timebase: queue wait + compute for smoke-rid-1, per-token decode
    # spans from the generate traffic (all head-kept at sample rate 1)
    trace = tele.chrome_trace()["traceEvents"]
    by_rid = [e for e in trace
              if e.get("args", {}).get("request_id") == "smoke-rid-1"]
    names = {e["name"] for e in by_rid}
    check("trace has queue-wait + compute spans for smoke-rid-1",
          {"serving.request.queue_wait",
           "serving.request.compute"} <= names, str(sorted(names)))
    shed_spans = [e for e in trace
                  if e.get("args", {}).get("request_id") == "smoke-rid-shed"]
    check("shed request's span is kept with the shed outcome",
          any(e.get("args", {}).get("outcome") == "shed:deadline"
              for e in shed_spans))
    decode = [e for e in trace
              if e["name"] == "serving.generate.decode_token"]
    check("trace has per-token decode spans for generate traffic",
          len(decode) >= 3, f"{len(decode)} decode-step spans")

    # flight recorder: the forced deadline shed above is on record, with
    # its cause, in the per-model debug dump
    code, text = http_get(
        f"{server.url}/v1/models/dense/debug/requests?last=64", use_curl)
    dump = json.loads(text) if code == 200 else {}
    recs = dump.get("requests", [])
    check("flight-recorder dump non-empty after the shed",
          code == 200 and len(recs) > 0, f"{len(recs)} records")
    check("shed record carries id + cause",
          any(r.get("id") == "smoke-rid-shed" and r.get("status") == "shed"
              and r.get("cause") == "deadline" for r in recs))
    check("ok records carry phase timings",
          any(r.get("status") == "ok" and r.get("compute_ms") is not None
              and r.get("total_ms", 0) >= r.get("compute_ms", 0)
              for r in recs))
    code, _text = http_get(
        f"{server.url}/v1/models/ghost/debug/requests", use_curl)
    check("debug dump for unknown model answers 404", code == 404)

    # SLO engine: /slo serves burn-rate math for a declared objective;
    # a synthetic budget-exhausted objective flips /healthz to 503
    from deeplearning4j_tpu.util import slo
    from deeplearning4j_tpu.util import telemetry as _tm

    slo.register(slo.SloObjective("smoke-avail", "availability",
                                  target=0.5, model="dense"))
    code, text = http_get(f"{server.url}/slo", use_curl)
    doc = json.loads(text) if code == 200 else {}
    objs = {o["name"]: o for o in doc.get("objectives", [])}
    ok_slo = (code == 200 and "smoke-avail" in objs
              and "60s" in objs["smoke-avail"]["windows"]
              and "burn_rate" in objs["smoke-avail"]["windows"]["60s"])
    check("/slo serves burn-rate windows for the objective", ok_slo)
    check("real traffic meets the smoke objective",
          objs.get("smoke-avail", {}).get("compliant") is True)
    code, text = http_get(f"{server.url}/metrics", use_curl)
    check("/metrics carries the SLO gauges",
          'dl4j_slo_burn_rate{slo="smoke-avail"' in text)

    # synthetic exhaustion: a 99.9% objective over counters we feed
    # directly — one baseline evaluation, then a burst of sheds
    slo.register(slo.SloObjective("smoke-exhausted", "availability",
                                  target=0.999, model="synthetic-smoke"))
    _tm.counter("serving.completed_total", 1, model="synthetic-smoke",
                lane="interactive")
    slo.get_engine().evaluate()
    _tm.counter("serving.shed_total", 9, model="synthetic-smoke",
                reason="deadline", lane="interactive")
    code, text = http_get(f"{server.url}/healthz", use_curl)
    health = json.loads(text) if text.strip().startswith("{") else {}
    exhausted = {o["name"]: o
                 for o in health.get("slo", {}).get("objectives", [])}
    check("exhausted budget flips /healthz to 503", code == 503,
          f"code {code}")
    check("/healthz slo section shows the exhausted objective",
          exhausted.get("smoke-exhausted", {}).get("exhausted") is True)
    check("/healthz check slo.smoke-exhausted is failing",
          health.get("checks", {}).get("slo.smoke-exhausted",
                                       {}).get("ok") is False)
    slo.reset()  # recovery: dropping the objectives restores the checks
    code, _text = http_get(f"{server.url}/healthz", use_curl)
    check("/healthz recovers after SLO reset", code == 200, f"code {code}")

    print("== graceful drain ==")
    server.request_drain()
    check("server drains clean", server.wait_drained(timeout=30))
    code, _body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                               {"inputs": xs[0].tolist()})
    check("post-drain request answers 503", code == 503, f"code {code}")
    server.stop()

    if _FAILED:
        print(f"SERVING SMOKE FAIL: {len(_FAILED)} checks failed: "
              f"{_FAILED}")
        return 1
    print("serving smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
