"""Host-pipeline A/B sweep: sync vs device-prefetch vs prefetch + multiproc ETL.

PR 1 attacked the device half of the step budget (fusion_sweep.py); this
harness measures the HOST half that ISSUE 2 builds: an injectable
slow-transform load is fed to the LeNet-5 train loop three ways —

  sync            batches transformed + staged in the fit() thread (the
                  pre-ISSUE-2 path): every step pays compute + ETL serially
  prefetch        AsyncDataSetIterator double-buffers ETL + device_put of
                  batch k+1 under batch k's compute (sync_every coalescing on)
  prefetch+mpetl  TransformProcess records ETL'd by the multiprocess
                  executor first (DL4J_TPU_ETL_WORKERS / --workers), then
                  prefetch-fed — the full ISSUE-2 pipeline

Methodology (BASELINE.md round-4/5): every per-batch cost is a TWO-POINT
FIT — wall(n_hi batches) − wall(n_lo batches) over (n_hi − n_lo) — which
cancels the pipeline ramp (first batch waits on the first transform) and
any fixed setup, the same cancellation fusion_sweep.py uses for the tunnel
round-trip. Each candidate is median-of-3 fits with the spread as ``noise``.

ETL load is injectable: ``--etl-ms`` per batch (default 0.8x the measured
compute step — heavy enough that sync pays ~1.8-2x, light enough to be
hideable) and ``--etl-load sleep|spin``. ``sleep`` models I/O-shaped ETL
(decode waits, network reads) and can overlap even on this 1-core host;
``spin`` models CPU-bound transforms, which a 1-core host CANNOT overlap —
running both makes the measurement ceiling explicit (docs/HOST_PIPELINE.md).

Usage::

    python benchmarks/host_pipeline_sweep.py                 # auto-sized
    python benchmarks/host_pipeline_sweep.py --etl-load spin # 1-core ceiling
    python benchmarks/host_pipeline_sweep.py --json sweep.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python benchmarks/host_pipeline_sweep.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _build_lenet, _med3  # noqa: E402


def _load_fn(kind: str, seconds: float):
    if kind == "sleep":
        return lambda: time.sleep(seconds)

    def spin():
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            pass

    return spin


class _SlowArrayIterator:
    """n batches of (x, y) with the injected per-batch ETL load applied in
    whatever thread iterates — fit()'s own thread on the sync leg, the
    prefetch worker on the async legs."""

    def __init__(self, x, y, batch, n_batches, load):
        self.x, self.y, self.batch, self.n, self.load = x, y, batch, n_batches, load

    def __iter__(self):
        from deeplearning4j_tpu.data import DataSet

        for i in range(self.n):
            self.load()
            j = (i * self.batch) % len(self.x)
            yield DataSet(self.x[j:j + self.batch], self.y[j:j + self.batch])

    def reset(self):
        pass

    def batch_size(self):
        return self.batch


class _RecordsIterator:
    """Transformed flat records → NHWC DataSet batches (the merge-back half
    of the multiprocess ETL leg)."""

    def __init__(self, records, batch, image_hw=28, num_classes=10):
        self.records, self.batch = records, batch
        self.hw, self.nc = image_hw, num_classes

    def __iter__(self):
        from deeplearning4j_tpu.data import DataSet

        for i in range(0, len(self.records), self.batch):
            chunk = self.records[i:i + self.batch]
            x = np.asarray([r[:-1] for r in chunk], np.float32).reshape(
                len(chunk), self.hw, self.hw, 1)
            y = np.eye(self.nc, dtype=np.float32)[
                np.asarray([int(r[-1]) for r in chunk])]
            yield DataSet(x, y)

    def reset(self):
        pass

    def batch_size(self):
        return self.batch


def _records(x, y, n_batches, batch):
    n = n_batches * batch
    flat = x[:n].reshape(n, -1)
    labels = np.argmax(y[:n], axis=1)
    return [list(map(float, flat[i])) + [int(labels[i])] for i in range(n)]


def _slow_tp(per_record_load):
    """TransformProcess with the injected load on one column — the
    'serialized transform' the worker processes apply."""
    from deeplearning4j_tpu.datavec import Schema, TransformProcess

    schema = Schema.builder().add_column_double("px0").build()  # probed col

    def loaded(v):
        per_record_load()
        return v

    # schema handling in this harness is positional: only column 0 is
    # declared/transformed, the rest pass through untouched
    return (TransformProcess.builder(schema)
            .double_column_transform("px0", loaded).build())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--n-lo", type=int, default=8)
    ap.add_argument("--n-hi", type=int, default=24)
    ap.add_argument("--etl-ms", type=float, default=None,
                    help="injected ETL per batch (default 0.8x measured step)")
    ap.add_argument("--etl-load", choices=("sleep", "spin"), default="sleep")
    ap.add_argument("--workers", type=int, default=None,
                    help="multiprocess ETL workers (default env/auto)")
    ap.add_argument("--json", type=str, default=None)
    args = ap.parse_args()

    import jax

    from deeplearning4j_tpu.data import AsyncDataSetIterator
    from deeplearning4j_tpu.datavec import MultiProcessTransformExecutor

    net = _build_lenet(sync_every=4)

    class _Observer:  # coalesced dispatch only runs with a listener
        def iteration_done(self, model, iteration, epoch):
            pass

    net.set_listeners(_Observer())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.batch * args.n_hi, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, len(x))]
    xd, yd = jax.device_put(x[:args.batch]), jax.device_put(y[:args.batch])
    for _ in range(4):
        net._fit_batch(xd, yd)
    float(net.score_value)

    def compute_wall(n):
        t0 = time.perf_counter()
        for _ in range(n):
            net._fit_batch(xd, yd)
        float(net.score_value)
        return time.perf_counter() - t0

    def fit_wall(make_iter, n):
        it = make_iter(n)
        t0 = time.perf_counter()
        net.fit(it, epochs=1)
        float(net.score_value)
        return time.perf_counter() - t0

    def slope(wall_at):
        """Two-point fit: per-batch cost with ramp/fixed costs cancelled."""
        def one():
            w_lo = wall_at(args.n_lo)
            w_hi = wall_at(args.n_hi)
            return (w_hi - w_lo) / (args.n_hi - args.n_lo)
        return _med3(one)

    step_s, step_noise = slope(compute_wall)
    etl_s = (args.etl_ms / 1e3) if args.etl_ms else 0.8 * step_s
    batch_load = _load_fn(args.etl_load, etl_s)
    per_record_load = _load_fn(args.etl_load, etl_s / args.batch)

    def slow_iter(n):
        return _SlowArrayIterator(x, y, args.batch, n, batch_load)

    rows = [{"candidate": "compute_only", "ms_per_batch": step_s * 1e3,
             "noise": step_noise, "ratio": 1.0}]

    legs = [
        ("sync", lambda n: fit_wall(slow_iter, n)),
        ("prefetch", lambda n: fit_wall(
            lambda m: AsyncDataSetIterator(slow_iter(m), buffer_size=2), n)),
    ]
    for name, wall_at in legs:
        s, nz = slope(wall_at)
        rows.append({"candidate": name, "ms_per_batch": s * 1e3, "noise": nz,
                     "ratio": s / step_s})

    # -- multiprocess ETL leg: transform wall (serial vs N workers) + the
    # end-to-end prefetch fit over the transformed records -----------------
    tp = _slow_tp(per_record_load)
    recs = _records(x, y, args.n_hi, args.batch)
    ex = MultiProcessTransformExecutor(tp, num_workers=args.workers,
                                       min_records_per_worker=8)
    outs = {}  # last output of each timed leg — compared below, not re-run

    def timed_into(key, fn):
        t0 = time.perf_counter()
        outs[key] = fn()
        return time.perf_counter() - t0

    t_serial, nz_s = _med3(lambda: timed_into("serial", lambda: tp.execute(recs)))
    t_mp, nz_m = _med3(lambda: timed_into("mp", lambda: ex.execute(recs)))
    if outs["mp"] != outs["serial"]:  # survives python -O, unlike assert
        raise RuntimeError("multiprocess ETL output != serial output")
    rows.append({"candidate": f"etl_serial ({len(recs)} records)",
                 "ms_per_batch": t_serial * 1e3 / args.n_hi, "noise": nz_s,
                 "ratio": None})
    rows.append({"candidate": f"etl_mp x{ex.num_workers}",
                 "ms_per_batch": t_mp * 1e3 / args.n_hi, "noise": nz_m,
                 "ratio": None, "etl_speedup": t_serial / t_mp})

    def mpetl_prefetch_wall(n):
        sub = recs[:n * args.batch]
        t0 = time.perf_counter()
        out = ex.execute(sub)
        net.fit(AsyncDataSetIterator(_RecordsIterator(out, args.batch),
                                     buffer_size=2), epochs=1)
        float(net.score_value)
        return time.perf_counter() - t0

    s, nz = slope(mpetl_prefetch_wall)
    rows.append({"candidate": "prefetch+mpetl", "ms_per_batch": s * 1e3,
                 "noise": nz, "ratio": s / step_s})

    result = {
        "config": {"batch": args.batch, "n_lo": args.n_lo, "n_hi": args.n_hi,
                   "etl_ms_per_batch": round(etl_s * 1e3, 3),
                   "etl_load": args.etl_load, "workers": ex.num_workers,
                   "host_cores": os.cpu_count(),
                   "platform": jax.default_backend()},
        "candidates": rows,
    }
    print(f"\nhost-pipeline sweep (two-point fit {args.n_lo}->{args.n_hi} "
          f"batches, median-of-3; ETL {args.etl_load} "
          f"{etl_s * 1e3:.1f} ms/batch; {os.cpu_count()}-core host)")
    print(f"{'candidate':<28} {'ms/batch':>9} {'noise':>8} {'x compute':>10}")
    for r in rows:
        ratio = "" if r["ratio"] is None else f"{r['ratio']:.3f}"
        extra = (f"  (speedup {r['etl_speedup']:.2f}x)"
                 if "etl_speedup" in r else "")
        noise = r["noise"].split(" ")[0]  # full string stays in the JSON
        print(f"{r['candidate']:<28} {r['ms_per_batch']:>9.2f} "
              f"{noise:>8} {ratio:>10}{extra}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=2)
        print(f"\nwrote {args.json}")


if __name__ == "__main__":
    main()
