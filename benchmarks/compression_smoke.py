"""CI smoke for the encoded gradient collectives (ISSUE 10).

Runs on 8 virtual CPU devices and asserts the three things CPU can honestly
prove about the compressed DP hot path (docs/DISTRIBUTED.md#gradient-
compression):

1. **Error-feedback conservation, bit-exact** — decode(encode(g, res, t)) +
   new_res == g + res with exact float equality, across gradient scales
   (the pow2-snapped threshold makes the residual subtraction exact).
2. **threshold→0 bit-identity** — the compressed wrapper at t=0 reproduces
   the uncompressed deterministic lane fit bit-for-bit (params + Adam
   moments + RNG key).
3. **Deterministic wire accounting** — on an adaptive-threshold fit the
   `parallel.allreduce_wire_bytes` counter is > 0 and the sparse wire
   ratio lands under 0.1 once the threshold reaches its target-sparsity
   band.

Exit 0 on success; any assertion failure exits non-zero (the CI legs in
.github/workflows/ci.yml + .github/ci_local.sh run this file directly).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.data import DataSet  # noqa: E402
from deeplearning4j_tpu.nn import (  # noqa: E402
    InputType, MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.ops import compression as C  # noqa: E402
from deeplearning4j_tpu.parallel import (  # noqa: E402
    ParallelWrapper, TrainingMesh)
from deeplearning4j_tpu.util import telemetry as tm  # noqa: E402


def _net(comp=None, threshold=1e-3, target=1e-3, n_in=64, width=256):
    b = NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
    if comp:
        b = b.grad_compression(comp, threshold=threshold,
                               target_sparsity=target)
    conf = (b.list()
            .layer(DenseLayer(n_in=n_in, n_out=width, activation="relu"))
            .layer(OutputLayer(n_in=width, n_out=8, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def check_conservation():
    rng = np.random.default_rng(0)
    for scale in (1e-6, 1e-3, 1.0, 1e3):
        for t in (1e-4, 1e-3, 0.05):
            g = jnp.asarray(rng.standard_normal(50000) * scale, jnp.float32)
            res = jnp.asarray(rng.standard_normal(50000) * scale * 0.5,
                              jnp.float32)
            carried = g + res
            q, new_res = C.threshold_encode_exact(carried, t)
            assert (np.asarray(q + new_res) == np.asarray(carried)).all(), \
                f"conservation violated at scale={scale} t={t}"
    g1 = jnp.asarray(rng.standard_normal(50000) * 0.01, jnp.float32)
    q, r, _ = C.onebit_encode(g1)
    assert (np.asarray(q + r) == np.asarray(g1)).all(), \
        "onebit conservation violated"
    print("PASS conservation: decode(encode)+residual == carried, bit-exact")


def check_t0_bit_identity():
    rng = np.random.default_rng(1)
    xs = rng.standard_normal((32, 64)).astype(np.float32)
    ys = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 32)]
    exact = _net()
    ParallelWrapper(exact, mesh=TrainingMesh(data=8), deterministic=True,
                    replicas=8, skew_every=0).fit([DataSet(xs, ys)],
                                                  epochs=3)
    comp = _net(comp="threshold", threshold=0.0)
    ParallelWrapper(comp, mesh=TrainingMesh(data=8), replicas=8,
                    skew_every=0).fit([DataSet(xs, ys)], epochs=3)
    for what, a, b in (("params", exact.params, comp.params),
                       ("opt", exact.opt_states, comp.opt_states)):
        for i, (u, v) in enumerate(zip(jax.tree_util.tree_leaves(a),
                                       jax.tree_util.tree_leaves(b))):
            assert (np.asarray(u) == np.asarray(v)).all(), (what, i)
    assert (np.asarray(exact._rng_key) == np.asarray(comp._rng_key)).all()
    print("PASS threshold->0 bit-identity with the uncompressed lane path")


def check_wire_ratio():
    rng = np.random.default_rng(2)
    xs = rng.standard_normal((64, 64)).astype(np.float32)
    ys = np.eye(8, dtype=np.float32)[rng.integers(0, 8, 64)]
    net = _net(comp="threshold", threshold=1e-3, target=1e-3,
               n_in=64, width=512)
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=8), skew_every=0)
    batches = [DataSet(xs[i:i + 8], ys[i:i + 8]) for i in range(0, 64, 8)]
    pw.fit(batches, epochs=6)  # let the threshold adapt to target
    stats = pw.compression_stats()
    assert stats["wire_bytes"] > 0, stats
    assert stats["ratio"] < 0.1, \
        f"wire ratio {stats['ratio']:.4f} not under 0.1: {stats}"
    counters = tm.get_telemetry().counters
    total = sum(v for (name, _), v in counters.items()
                if name == "parallel.allreduce_wire_bytes_total")
    assert total > 0, "wire-bytes counter never incremented"
    print(f"PASS wire accounting: ratio {stats['ratio']:.4f} < 0.1, "
          f"counter {total:.0f} B, adapted threshold "
          f"{stats['threshold']:.2e}")


def main():
    assert len(jax.devices()) >= 8, jax.devices()
    check_conservation()
    check_t0_bit_identity()
    check_wire_ratio()
    print("compression smoke: ALL PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
