"""Perf-regression gate over the committed BENCH trajectory.

Every round the driver commits a ``BENCH_r<NN>.json`` record (one
``bench.py`` run: headline metric + ``extra_metrics``, each with a
median-of-3 ``value`` and an explicit ``noise`` spread). This gate turns
that trajectory from prose into a machine check: it computes a
**noise-aware best-known band** per metric and fails loudly when a
candidate run regresses beyond it — so a future PR's perf claims are
verified the same way its correctness claims are (the CI leg in
``.github/workflows/ci.yml`` / ``.github/ci_local.sh``).

Band rule, per metric::

    best      = best value over the trajectory (direction-aware)
    tol       = noise(best record) + noise(candidate) + slack
    bound     = best * (1 - tol)      # higher-is-better metrics
                best * (1 + tol)      # lower-is-better (overhead ratios)
    regressed = candidate beyond bound

Noise fractions come from each record's own ``noise`` field ("±7.2%
(3-sample spread/2)"); records predating the noise field get
``--default-noise`` (5%). The additive ``--slack`` (2%) absorbs
host-to-host drift. The bound is intentionally one-sided: a new best is a
pass (and tightens the band once committed), only a regression fails.
Metrics in ``HOST_CONDITION_FLOOR`` gate against an absolute floor
instead — their committed values track the shared host's scheduling
weather, not the code (see the constant's comment).

Modes:

- ``--ci``: gate the LATEST committed record against the band of the whole
  trajectory (must pass on a healthy repo), then run the built-in
  self-test — re-gate with a synthetically regressed copy of the headline
  metric and require the gate to FAIL. A gate that cannot fail is not a
  gate; CI proves both directions every run.
- ``--check FILE|-``: gate a fresh ``bench.py`` output (its single JSON
  line, or a committed-record wrapper) — the local pre-commit workflow.
- default (no mode): report the bands.

Exit status: 0 = pass, 1 = regression (or a self-test that failed to
fail), 2 = usage/data errors.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_NOISE = 0.05   # records predating the explicit noise field
DEFAULT_SLACK = 0.02   # additive cross-session drift allowance

# Metrics where SMALLER is better (overhead/ratio style); everything else
# (throughput, efficiency) is higher-is-better.
LOWER_BETTER = {
    "host_pipeline_overlap",
    "telemetry_overhead",
    "recompile_overhead",
    "cost_attribution_overhead",
    "elastic_overhead",
    "zero_optimizer_memory_bytes_per_device",
    # serving tier (ISSUE 8): request latency gates downward, its QPS
    # companion (serving_qps) gates upward via the higher-is-better default
    "serving_p99_latency_ms",
    # kernel engine (ISSUE 9): the update phase's fraction of attributed
    # device time — the fused donated optimizer apply must keep it down
    "optimizer_update_ms_share",
    # encoded gradient collectives (ISSUE 10): one worker's encoded
    # all-reduce payload vs its dense fp32 gradient on the 25M-param DP
    # workload — the wire math is deterministic, so this band is tight
    "encoded_allreduce_wire_bytes_ratio",
    # autotuning subsystem (ISSUE 11): what consulting the tuning
    # database costs kernel_impl=auto dispatch at trace time — one
    # signature build + one in-memory-cached lookup; target ≤ 1.05x
    "autotune_dispatch_overhead",
    # request-scope tracing (ISSUE 12): the r13 mixed serving workload
    # with EVERY request emitting phase spans + flight-recorder records
    # (DL4J_TPU_TRACE_SAMPLE=1) over tracing off — the worst case of the
    # default 2% head sample; target ≤ 1.05x, the r9 telemetry_overhead
    # convention
    "request_tracing_overhead",
    # serving resilience layer (ISSUE 13): what the supervised watchdog +
    # per-model circuit breaker cost the mixed serving workload vs both
    # off — target ≤ 1.05x, the r9 overhead convention
    "serving_resilience_overhead",
    # and what a rolling-reload storm (restore + shadow warmup + canary +
    # swap) adds to the traffic's p99 tail vs a duration-matched steady
    # window — ms, floored at 0.5 so the multiplicative band stays sane
    # when the storm is within timer noise of free
    "serving_reload_p99_delta_ms",
    # pipeline-parallel fit() (ISSUE 14, BENCH_r10 headline):
    # param+optimizer bytes ONE device holds for the stage-dominated net
    # on the (data=2, model=2, pipe=2) mesh — stacked stage params
    # P('pipe') + ZeRO moments; deterministic byte accounting, so this
    # band is exact — a regression means the placement stopped sharding
    "pipeline_param_bytes_per_device",
    # and the GPipe schedule's bubble fraction (S-1)/(n_micro+S-1) at the
    # committed config — schedule arithmetic, not wall-clock (CPU cannot
    # rank bubbles; the r6 honesty convention)
    "pipeline_bubble_fraction",
    # disaggregated fleet (ISSUE 18): what the routing tier (rendezvous
    # hash + header relay + pooled proxy hop) adds to a serial request's
    # p50 over posting straight to the worker, in ms
    "fleet_routing_overhead_ms",
}

# The decode-path metrics (ISSUE 15, BENCH_r11 headline) gate through the
# higher-is-better default: concurrent_streams_per_device is deterministic
# block accounting of the paged KV pool (±0% — a drop means the pool
# stopped paging, gated ABOVE the contiguous-cache ceiling by its
# vs_baseline ratio), and speculative_decode_tokens_per_sec pins the
# spec-path throughput with the honest CPU A/B in the record note (a
# random-init draft accepts ~nothing on CPU; the metric exists so the
# machinery cannot silently regress, not to rank the chip-side win).

# Metrics a candidate run may NEVER drop (missing == fail even without
# --strict): the scaling-efficiency number is the r12 GSPMD rewrite's
# contract — a run that silently stops reporting it would let efficiency
# regress unobserved (ISSUE 7 satellite).
CRITICAL = {
    "dp_sharding_efficiency_8dev_virtual_cpu",
    # the disaggregated fleet's contract (ISSUE 18): a run that silently
    # stops reporting scaling efficiency would let the routing tier's
    # throughput retention regress unobserved
    "fleet_qps_scaling_efficiency",
}

# Host-condition-sensitive metrics gate against an ABSOLUTE FLOOR instead
# of the best-known band. bench_scaling's own contract is "only the
# same-host trend is meaningful": the committed trajectory spans
# 0.5168 (r03) to 1.0591 (r08) for the SAME code path as the shared
# 1-core host's scheduling weather changes between sessions — re-running
# the r11 seed commit on a slow-weather host measures ~0.68 where its
# committed record says 0.9988, so a best-known band would fail healthy
# code whenever the host regresses. The floor sits below the worst
# committed value minus its noise: a true sharding breakage (partitioner
# stops sharding, collective blowup) collapses the ratio far below it,
# while host weather cannot. Floor metrics stay CRITICAL — missing is
# still fatal.
HOST_CONDITION_FLOOR = {
    "dp_sharding_efficiency_8dev_virtual_cpu": 0.45,
    # fleet QPS efficiency is normalized by min(N, host_cores) (bench.py
    # bench_fleet, the honest-CPU rule) but still times real HTTP traffic
    # on a shared host, so it floors at the ISSUE 18 acceptance bound
    # rather than banding against the best-known 0.97: a routing-tier
    # breakage (serialized proxying, thrashing respawns, lost keep-alive)
    # collapses it far below 0.6, host weather does not
    "fleet_qps_scaling_efficiency": 0.6,
}

_NOISE_RE = re.compile(r"[+±]?\s*([0-9.]+)\s*%")


def parse_noise(noise_field) -> Optional[float]:
    """'±7.2% (3-sample spread/2)' -> 0.072; None/garbage -> None."""
    if not noise_field:
        return None
    m = _NOISE_RE.search(str(noise_field))
    if not m:
        return None
    try:
        return float(m.group(1)) / 100.0
    except ValueError:
        return None


def _metric_rows(parsed: dict) -> List[dict]:
    rows = [parsed] + list(parsed.get("extra_metrics") or [])
    return [r for r in rows
            if isinstance(r, dict) and "metric" in r
            and isinstance(r.get("value"), (int, float))]


def load_record(obj: dict, label: str) -> Dict[str, Tuple[float, Optional[float]]]:
    """One committed BENCH wrapper ({"n":.., "parsed": {...}}) or raw
    bench.py result dict -> {metric: (value, noise_frac)}."""
    parsed = obj.get("parsed", obj)
    if not isinstance(parsed, dict) or "metric" not in parsed:
        raise ValueError(f"{label}: no bench metrics found")
    return {r["metric"]: (float(r["value"]), parse_noise(r.get("noise")))
            for r in _metric_rows(parsed)}


def load_trajectory(paths: List[str]):
    """[(label, {metric: (value, noise)})] in round order."""
    out = []
    for p in sorted(paths):
        with open(p) as f:
            obj = json.load(f)
        try:
            out.append((os.path.basename(p), load_record(obj, p)))
        except ValueError as e:
            print(f"regression_gate: skipping {p}: {e}", file=sys.stderr)
    return out


def best_known(trajectory, metric: str):
    """(best_value, best_noise, best_label) direction-aware, or None."""
    lower = metric in LOWER_BETTER
    best = None
    for label, metrics in trajectory:
        if metric not in metrics:
            continue
        value, noise = metrics[metric]
        if best is None \
                or (value < best[0] if lower else value > best[0]):
            best = (value, noise, label)
    return best


def gate(trajectory, candidate: Dict[str, Tuple[float, Optional[float]]],
         default_noise: float = DEFAULT_NOISE,
         slack: float = DEFAULT_SLACK) -> List[dict]:
    """Evaluate every trajectory metric against the candidate. Returns one
    result dict per metric: status in {ok, regressed, missing, new}."""
    results = []
    seen = set()
    for metric in {m for _, ms in trajectory for m in ms}:
        seen.add(metric)
        best = best_known(trajectory, metric)
        if best is None:
            continue
        best_value, best_noise, best_label = best
        lower = metric in LOWER_BETTER
        if metric not in candidate:
            results.append({"metric": metric, "status": "missing",
                            "best": best_value, "best_round": best_label})
            continue
        value, noise = candidate[metric]
        if metric in HOST_CONDITION_FLOOR:
            floor = HOST_CONDITION_FLOOR[metric]
            results.append({
                "metric": metric,
                "status": "regressed" if value < floor else "ok",
                "value": value,
                "best": best_value,
                "best_round": best_label,
                "bound": floor,
                "tolerance_frac": 0.0,
                "direction": "floor",
            })
            continue
        tol = ((best_noise if best_noise is not None else default_noise)
               + (noise if noise is not None else default_noise) + slack)
        bound = best_value * (1 + tol) if lower else best_value * (1 - tol)
        regressed = value > bound if lower else value < bound
        results.append({
            "metric": metric,
            "status": "regressed" if regressed else "ok",
            "value": value,
            "best": best_value,
            "best_round": best_label,
            "bound": bound,
            "tolerance_frac": round(tol, 4),
            "direction": "lower" if lower else "higher",
        })
    for metric, (value, _noise) in candidate.items():
        if metric not in seen:
            results.append({"metric": metric, "status": "new",
                            "value": value})
    return sorted(results, key=lambda r: r["metric"])


def render(results: List[dict]) -> str:
    lines = []
    for r in results:
        if r["status"] == "ok":
            how = ("host-condition floor" if r["direction"] == "floor"
                   else f"{r['direction']}-is-better")
            lines.append(
                f"  OK        {r['metric']}: {r['value']:g} within band "
                f"(best {r['best']:g} @ {r['best_round']}, bound "
                f"{r['bound']:g}, {how})")
        elif r["status"] == "regressed":
            how = ("host-condition floor" if r["direction"] == "floor"
                   else f"{r['direction']}-is-better")
            lines.append(
                f"  REGRESSED {r['metric']}: {r['value']:g} beyond bound "
                f"{r['bound']:g} (best {r['best']:g} @ {r['best_round']}, "
                f"tol {100 * r['tolerance_frac']:.1f}%, "
                f"{how})")
        elif r["status"] == "missing":
            lines.append(
                f"  MISSING   {r['metric']}: not in candidate run "
                f"(best {r['best']:g} @ {r['best_round']})")
        else:
            lines.append(
                f"  NEW       {r['metric']}: {r['value']:g} "
                "(no trajectory yet)")
    return "\n".join(lines)


def _passed(results: List[dict], strict: bool) -> bool:
    bad = {"regressed"} | ({"missing"} if strict else set())
    for r in results:
        if r["status"] in bad:
            return False
        if r["status"] == "missing" and r["metric"] in CRITICAL:
            return False
    return True


def _load_candidate_file(path: str) -> Dict[str, Tuple[float, Optional[float]]]:
    text = sys.stdin.read() if path == "-" else open(path).read()
    # accept either a full JSON document or bench.py's stdout (JSON line
    # surrounded by logging noise)
    try:
        return load_record(json.loads(text), path)
    except (json.JSONDecodeError, ValueError):
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return load_record(json.loads(line), path)
        raise ValueError(f"{path}: no JSON bench record found")


def self_test(trajectory, default_noise: float, slack: float) -> bool:
    """Prove the gate FAILS on an injected regression: take the latest
    record, push its headline (first) metric far beyond the band, and
    require a 'regressed' verdict. Returns True when the gate behaves."""
    label, latest = trajectory[-1]
    metric = next(iter(latest))
    value, noise = latest[metric]
    lower = metric in LOWER_BETTER
    corrupted = dict(latest)
    corrupted[metric] = (value * (3.0 if lower else 1.0 / 3.0), noise)
    results = gate(trajectory, corrupted,
                   default_noise=default_noise, slack=slack)
    verdicts = {r["metric"]: r["status"] for r in results}
    ok = verdicts.get(metric) == "regressed"
    print(f"self-test: injected {metric} = {corrupted[metric][0]:g} "
          f"(was {value:g}) -> {verdicts.get(metric)} "
          f"[{'ok' if ok else 'GATE DID NOT FIRE'}]")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench-glob", default=os.path.join(
        REPO_ROOT, "BENCH_r*.json"),
        help="committed trajectory records (default: repo BENCH_r*.json)")
    ap.add_argument("--ci", action="store_true",
                    help="gate the latest committed record + run the "
                         "injected-regression self-test")
    ap.add_argument("--check", metavar="FILE",
                    help="gate a fresh bench.py output file ('-' = stdin)")
    ap.add_argument("--strict", action="store_true",
                    help="metrics missing from the candidate fail the gate")
    ap.add_argument("--default-noise", type=float, default=DEFAULT_NOISE)
    ap.add_argument("--slack", type=float, default=DEFAULT_SLACK)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable results on stdout")
    args = ap.parse_args(argv)

    paths = glob.glob(args.bench_glob)
    trajectory = load_trajectory(paths)
    if not trajectory:
        print(f"regression_gate: no BENCH records match {args.bench_glob}",
              file=sys.stderr)
        return 2

    if args.check:
        try:
            candidate = _load_candidate_file(args.check)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"regression_gate: {e}", file=sys.stderr)
            return 2
        label = args.check
    else:
        label, candidate = trajectory[-1]

    results = gate(trajectory, candidate,
                   default_noise=args.default_noise, slack=args.slack)
    if args.json:
        print(json.dumps({"candidate": label, "results": results}))
    else:
        print(f"regression gate: candidate = {label}, trajectory = "
              f"{len(trajectory)} records")
        print(render(results))
    ok = _passed(results, args.strict)
    if not ok:
        print("regression gate: FAIL", file=sys.stderr)
        return 1
    if args.ci:
        if not self_test(trajectory, args.default_noise, args.slack):
            print("regression gate: self-test FAIL — the gate did not "
                  "flag an injected regression", file=sys.stderr)
            return 1
    # keep stdout pure JSON under --json (machine consumers parse it whole)
    print("regression gate: PASS",
          file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
