"""Whole-conv-mix isolated measurement (the BASELINE.md round-5 conv
re-derivation; run on the real chip via the axon tunnel).

ONE jitted scan whose body runs every ResNet-50 conv instance
(count-weighted, per-instance weights so CSE cannot merge them), fwd and
fwd+bwd variants. Per-iter time is ~tens of ms, so the two-point fit sits
far above tunnel jitter. The conv consumer is sum(y*y): a plain
sum(conv(x, w)) folds algebraically in XLA and reports impossible TF/s.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

B = 256
SHAPES = [
    (224, 3, 64, 7, 2, 1), (56, 64, 64, 1, 1, 1), (56, 256, 64, 1, 1, 2),
    (56, 64, 64, 3, 1, 3), (56, 64, 256, 1, 1, 3), (56, 64, 256, 1, 1, 1),
    (56, 256, 128, 1, 1, 1), (56, 128, 128, 3, 2, 1), (28, 512, 128, 1, 1, 3),
    (28, 128, 128, 3, 1, 3), (28, 128, 512, 1, 1, 4), (56, 256, 512, 1, 2, 1),
    (28, 512, 256, 1, 1, 1), (28, 256, 256, 3, 2, 1), (14, 1024, 256, 1, 1, 5),
    (14, 256, 256, 3, 1, 5), (14, 256, 1024, 1, 1, 6), (28, 512, 1024, 1, 2, 1),
    (14, 1024, 512, 1, 1, 1), (14, 512, 512, 3, 2, 1), (7, 2048, 512, 1, 1, 2),
    (7, 512, 512, 3, 1, 2), (7, 512, 2048, 1, 1, 3), (14, 1024, 2048, 1, 2, 1),
]

rng = np.random.default_rng(0)
xs, ws, flops = [], [], 0
for h, cin, cout, k, s, count in SHAPES:
    xs.append(jnp.asarray(rng.normal(size=(B, h, h, cin)), jnp.bfloat16))
    # one DISTINCT weight tensor per instance: the conv must run count
    # times (same weights would CSE into one conv)
    ws.append([jnp.asarray(rng.normal(size=(k, k, cin, cout)) * 0.05,
                           jnp.bfloat16) for _ in range(count)])
    flops += count * 2 * B * (h // s) ** 2 * k * k * cin * cout


def convs(xs, ws, eps):
    acc = jnp.float32(0)
    for (h, cin, cout, k, s, count), x, wlist in zip(SHAPES, xs, ws):
        for w in wlist:
            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NHWC", "HWIO", "NHWC"))
            y = lax.conv_general_dilated(x + eps.astype(x.dtype), w,
                                         (s, s), "SAME",
                                         dimension_numbers=dn)
            # nonlinear reduce: sum(conv) folds algebraically; y*y cannot
            acc = acc + jnp.sum(
                y.astype(jnp.float32) * y.astype(jnp.float32))
    return acc


def train(xs, ws, eps):
    def loss(ws):
        return convs(xs, ws, eps)
    l, gs = jax.value_and_grad(loss)(ws)
    return l + sum(jnp.sum(g).astype(jnp.float32)
                   for gl in gs for g in gl)


def per_iter(fn, klo=2, khi=8):
    def make(iters):
        @jax.jit
        def many(xs, ws):
            def body(c, s):
                return c + fn(xs, ws, s), None
            out, _ = lax.scan(body, jnp.float32(0),
                              jnp.arange(iters, dtype=jnp.float32) * 1e-6)
            return out
        return many

    lo, hi = make(klo), make(khi)
    float(lo(xs, ws)); float(hi(xs, ws))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter(); float(lo(xs, ws)); tl = time.perf_counter() - t0
        t0 = time.perf_counter(); float(hi(xs, ws)); th = time.perf_counter() - t0
        if th > tl:
            best = min(best, (th - tl) / (khi - klo))
    if best == float("inf"):
        raise RuntimeError(
            "two-point fit degenerate in all 3 attempts (jitter exceeds "
            "the device-time delta) — refusing to report")
    return best


fwd = per_iter(convs)
tr = per_iter(train)
print(f"isolated conv mix (count-weighted, B={B}, bf16):")
print(f"  fwd      {fwd*1e3:7.2f} ms/iter  -> {flops/fwd/1e12:5.1f} TF/s")
print(f"  fwd+bwd  {tr*1e3:7.2f} ms/iter  -> {3*flops/tr/1e12:5.1f} TF/s "
      f"(3x fwd FLOPs)")
print(f"  fwd FLOPs of the mix: {flops/1e12:.2f} TF")
