"""One-command autotuning sweep: measure every registered knob space and
persist equivalence-gated winners in the tuning database.

This is the harvest command for ROADMAP item 2 and the standing hardware
debt: five eras of perf work ended with "CPU proves equivalence but
cannot rank" (remat policies, kernel_impl/tile shapes, XLA flags, bucket
sets, compression_hosts). On a CPU container this script proves the
machinery end-to-end — deterministic candidate sets, every admitted
candidate equivalence-checked against the exact path, winners committed
atomically, a warm re-run measuring NOTHING; on the first real-TPU
session the SAME command sweeps the real chip and flips every deferred
default with committed evidence:

    DL4J_TPU_TUNING_DB=tuning_db python benchmarks/autotune.py

Then commit the database directory — ``auto`` dispatch and conf-time
defaulting consult it at trace time from then on (docs/AUTOTUNE.md).

Declared-but-unmeasurable spaces (xla_flags: needs subprocess isolation
— use benchmarks/fusion_sweep.py; bucket_sets: needs a recorded length
distribution; compression_hosts: needs real DCN) are listed with their
reasons, never silently skipped.

Self-test hooks (exercised by benchmarks/autotune_smoke.py and the CI
leg): ``--plant-slow LABEL:SECONDS`` adds a per-call sleep to one
candidate (it must demonstrably LOSE), ``--plant-wrong LABEL`` perturbs
one candidate's outputs (the equivalence gate must REJECT it). Both act
on the real measurement path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_plants(args):
    handicap = {}
    for spec in args.plant_slow or []:
        label, _, secs = spec.rpartition(":")
        if not label:
            raise SystemExit(f"--plant-slow wants LABEL:SECONDS, got {spec!r}")
        handicap[label] = float(secs)
    corrupt = {}
    for label in args.plant_wrong or []:
        def bad(outputs, _label=label):
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(outputs)
            leaves = [leaves[0] + 1.0] + leaves[1:]
            return jax.tree_util.tree_unflatten(treedef, leaves)

        corrupt[label] = bad
    return handicap, corrupt


def _tuning_counters():
    from deeplearning4j_tpu.util import telemetry as tm

    snap = tm.get_telemetry().snapshot()
    return {n: v for n, v in snap["counters"].items()
            if n.startswith("tuning.")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--db", default=None,
                    help="tuning database directory (default: "
                         "DL4J_TPU_TUNING_DB or ./tuning_db)")
    ap.add_argument("--spaces", default=None,
                    help="comma-separated space names (default: every "
                         "measurable registered space)")
    ap.add_argument("--search", choices=("grid", "random"), default="grid")
    ap.add_argument("--samples", type=int, default=6,
                    help="random-mode candidate budget per context")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--runs", type=int, default=3,
                    help="median-of-N timing runs")
    ap.add_argument("--min-window", type=float, default=0.05,
                    help="minimum timed window seconds (two-point fit)")
    ap.add_argument("--force", action="store_true",
                    help="re-measure even when the database is warm")
    ap.add_argument("--plant-slow", action="append", metavar="LABEL:SECS",
                    help="self-test: handicap one candidate per call")
    ap.add_argument("--plant-wrong", action="append", metavar="LABEL",
                    help="self-test: corrupt one candidate's outputs")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    # honor an explicit JAX_PLATFORMS over this image's preset platform
    # (the conftest.py discovery: the env var alone does not win over the
    # preset axon platform; the config update does). The harvest command
    # on the chip simply leaves JAX_PLATFORMS unset.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    from deeplearning4j_tpu import tuning

    db_dir = args.db or tuning.database_dir() or "tuning_db"
    db = tuning.set_database(db_dir)
    driver = tuning.MeasurementDriver(
        db, search=args.search, samples=args.samples, seed=args.seed,
        runs=args.runs, min_window_s=args.min_window)
    handicap, corrupt = _parse_plants(args)

    names = ([s.strip() for s in args.spaces.split(",") if s.strip()]
             if args.spaces else tuning.measurable_spaces())
    report = {"db": db.dir, "spaces": [], "declared": []}
    failures = 0
    t_start = time.time()

    for name in names:
        space = tuning.get_space(name)
        if not space.measurable:
            report["declared"].append(
                {"space": name, "requires": space.requires,
                 "candidates": [c.label for c in space.enumerate({})]})
            continue
        for ctx in space.default_contexts():
            t0 = time.time()
            try:
                entry = driver.sweep(space, ctx, force=args.force,
                                     handicap=handicap, corrupt=corrupt)
            except RuntimeError as e:
                failures += 1
                report["spaces"].append(
                    {"space": name, "ctx": ctx, "error": str(e)})
                continue
            rows = entry.get("measured", [])
            report["spaces"].append({
                "space": name,
                "sig": space.key(ctx).sig,
                "status": entry["status"],
                "winner": entry["winner"],
                "speedup_vs_default": entry.get("speedup_vs_default"),
                "admitted": sum(1 for r in rows if r.get("admitted")),
                "rejected": sum(1 for r in rows
                                if r.get("admitted") is False),
                "sweep_seconds": round(time.time() - t0, 2),
            })

    # the remaining declared spaces always appear in the report — a
    # deferred decision is surfaced, never silently dropped
    if not args.spaces:
        for name in tuning.space_names():
            space = tuning.get_space(name)
            if not space.measurable and name not in [
                    d["space"] for d in report["declared"]]:
                report["declared"].append(
                    {"space": name, "requires": space.requires,
                     "candidates": [c.label for c in space.enumerate({})]})

    report["counters"] = _tuning_counters()
    report["db_stats"] = db.stats()
    report["wall_seconds"] = round(time.time() - t_start, 2)

    if args.json:
        print(json.dumps(report))
    else:
        import jax

        print(f"autotune: backend={jax.default_backend()} "
              f"db={db.dir} search={args.search} seed={args.seed}")
        for row in report["spaces"]:
            if "error" in row:
                print(f"  FAIL  {row['space']}: {row['error']}")
                continue
            w = row["winner"]
            print(f"  {row['status']:<9} {row['space']:<16} "
                  f"{row['sig']:<44} -> {w['label']} "
                  f"({w['ms']:.4g} ms, x{row['speedup_vs_default']:g} vs "
                  f"default; {row['admitted']} admitted, "
                  f"{row['rejected']} rejected)")
        for row in report["declared"]:
            print(f"  declared  {row['space']:<16} requires "
                  f"{row['requires']} ({len(row['candidates'])} candidates)")
        c = report["counters"]
        print(f"  counters: measurements={c.get('tuning.measurements_total', 0):g} "
              f"lookups={c.get('tuning.lookups_total', 0):g} "
              f"hits={c.get('tuning.hits_total', 0):g} "
              f"equivalence_rejects={c.get('tuning.equivalence_rejects_total', 0):g}")
        print(f"  db: {report['db_stats']['entries']} entries in "
              f"{report['db_stats']['dir']} "
              f"({report['wall_seconds']}s total)")
        if jax.default_backend() == "cpu":
            print("  NOTE: CPU container — winners rank the CPU backend "
                  "only (entries key backend+topology); run this command "
                  "on the chip to harvest the standing hardware debt "
                  "(docs/AUTOTUNE.md).")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
