"""Fusion-boundary A/B sweep for the flagship ResNet-50 train step.

BASELINE.md round-5 decomposed the 107.3 ms device step into ≈35.5 ms
irreducible conv compute + ≈35.2 ms bandwidth-floor non-conv work + ≈36 ms
fusion-context cost (convs in the fused step run at ~half their isolated
efficiency). This harness times the CANDIDATES that attack that cost —
per-stage selective-remat policies, optimization-barrier placement, and
process-global XLA flag sets — with the repo's established same-session
methodology and emits a ranked table for BASELINE.md.

Methodology (BASELINE.md round-4/5): every timing is a TWO-POINT FIT —
wall(K_hi steps) − wall(K_lo steps) over (K_hi − K_lo) steps with completion
forced by a host fetch — which cancels the session-variable tunnel round-trip
latency (measured 4–135 ms across sessions). Each candidate is median-of-3
fits with the spread reported as ``noise``. When an XPlane device plane
exists (TPU runs), a short trace adds the per-step device total; the CPU
backend has no device plane, so the fallback is the host plane's
``ThunkExecutor::Execute`` total — the CPU backend's compiled-module
execution event, summed across worker threads (it can exceed wall time
under intra-op parallelism; labeled ``xplane_plane: "host:thunks"``).

XLA flag candidates are process-global and unknown flags ABORT the XLA
client, so they run in a fresh subprocess (``--one``); a flag set the build
rejects is recorded as invalid rather than crashing the sweep.

Usage::

    python benchmarks/fusion_sweep.py                  # auto-sized sweep
    python benchmarks/fusion_sweep.py --batch 256 --image 224 --classes 1000
    python benchmarks/fusion_sweep.py --json sweep.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

# runnable as `python benchmarks/fusion_sweep.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (name, remat_policy, stage_barriers) — the in-process candidates
POLICY_CANDIDATES = [
    ("baseline", None, False),
    ("remat:full_stage", "full", False),
    ("remat:save_conv", "save_conv", False),
    ("remat:save_conv_dots", "save_conv_dots", False),
    ("remat:save_all", "save_all", False),
    ("barriers:stage", None, True),
    ("remat:save_conv+barriers", "save_conv", True),
]


def _build_net(policy, barriers, batch, image, classes, dtype):
    from deeplearning4j_tpu.zoo import ResNet50

    net = ResNet50(num_classes=classes, input_shape=(image, image, 3),
                   compute_dtype=dtype, remat_policy=policy,
                   stage_barriers=barriers).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, image, image, 3)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]
    return net, x, y


def _steps_wall(net, x, y, k):
    """Wall time of k pipelined steps, completion forced by the score fetch."""
    t0 = time.perf_counter()
    for _ in range(k):
        net._fit_batch(x, y)
    float(net.score_value)
    return time.perf_counter() - t0


def measure(policy, barriers, *, batch, image, classes, dtype, k_lo, k_hi,
            repeats=3, xplane=True):
    """One candidate -> dict with per-step ms (two-point fit, median-of-N),
    noise fraction, and the XPlane device total when a device plane exists."""
    import jax

    from deeplearning4j_tpu.util.profiler import (device_trace,
                                                  xplane_device_ms,
                                                  xplane_event_ms)

    net, x, y = _build_net(policy, barriers, batch, image, classes, dtype)
    x = jax.device_put(x)
    y = jax.device_put(y)
    for _ in range(3):  # warm past compile + sharding commitment
        net._fit_batch(x, y)
    float(net.score_value)
    fits = []
    for _ in range(repeats):
        t_lo = _steps_wall(net, x, y, k_lo)
        t_hi = _steps_wall(net, x, y, k_hi)
        if t_hi > t_lo:
            fits.append((t_hi - t_lo) / (k_hi - k_lo))
    if not fits:
        raise RuntimeError(
            "two-point fit degenerate in every repeat (jitter exceeds the "
            "step-time delta) — refusing to report")
    fits.sort()
    med = fits[len(fits) // 2]
    noise = (fits[-1] - fits[0]) / 2.0 / med if len(fits) > 1 else 0.0
    dev_ms, plane = None, None
    if xplane:
        with tempfile.TemporaryDirectory() as d:
            with device_trace(d):
                _steps_wall(net, x, y, 3)
            ms = xplane_device_ms(d)
            if ms > 0:
                dev_ms, plane = round(ms / 3.0, 3), "device"
            else:
                # CPU backend: no device plane exists. The honest stand-in is
                # the host plane's ThunkExecutor::Execute total — the CPU
                # backend's compiled-module execution event, summed across
                # worker threads (so it can EXCEED wall time under intra-op
                # parallelism; compare candidates, not against step_ms).
                ms = xplane_event_ms(d, "ThunkExecutor::Execute")
                if ms > 0:
                    dev_ms, plane = round(ms / 3.0, 3), "host:thunks"
    return {
        "step_ms": round(med * 1e3, 3),
        "img_per_sec": round(batch / med, 1),
        "noise_frac": round(noise, 4),
        "xplane_ms": dev_ms,
        "xplane_plane": plane,
        "fits_ms": [round(f * 1e3, 3) for f in fits],
    }


def _run_flag_candidate(name, flags, args):
    """Run one candidate in a subprocess with XLA_FLAGS appended (flags are
    process-global; unknown ones abort the client — per-build validity is
    part of the result)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
    spec = {"policy": None, "barriers": False, "batch": args.batch,
            "image": args.image, "classes": args.classes, "dtype": args.dtype,
            "k_lo": args.k_lo, "k_hi": args.k_hi}
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--one", json.dumps(spec)],
        env=env, capture_output=True, text=True, timeout=3600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        tail = (out.stderr or out.stdout).strip().splitlines()[-1:]
        return {"error": f"rejected by this build: {' '.join(tail)[:200]}"}
    return json.loads(lines[-1])


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--image", type=int, default=None)
    ap.add_argument("--classes", type=int, default=None)
    ap.add_argument("--dtype", default=None)
    ap.add_argument("--k-lo", type=int, default=None)
    ap.add_argument("--k-hi", type=int, default=None)
    ap.add_argument("--json", default=None, help="write full results here")
    ap.add_argument("--skip-flags", action="store_true",
                    help="skip the subprocess XLA-flag candidates")
    ap.add_argument("--one", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.one:  # subprocess worker: one candidate, one JSON line
        spec = json.loads(args.one)
        r = measure(spec["policy"], spec["barriers"], batch=spec["batch"],
                    image=spec["image"], classes=spec["classes"],
                    dtype=spec["dtype"], k_lo=spec["k_lo"], k_hi=spec["k_hi"])
        print(json.dumps(r))
        return

    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # Full flagship config on the chip; CPU-sized for harness validation
    # (fusion-context numbers are only meaningful on the device the step
    # targets — the CPU run proves the harness, not the policies).
    args.batch = args.batch or (256 if on_tpu else 4)
    args.image = args.image or (224 if on_tpu else 32)
    args.classes = args.classes or (1000 if on_tpu else 16)
    args.dtype = args.dtype or ("bfloat16" if on_tpu else "float32")
    args.k_lo = args.k_lo or (8 if on_tpu else 1)
    args.k_hi = args.k_hi or (40 if on_tpu else 4)

    from deeplearning4j_tpu.util.xla_tuning import XLA_FLAG_CANDIDATES

    results = []
    for name, policy, barriers in POLICY_CANDIDATES:
        print(f"[sweep] {name} ...", file=sys.stderr, flush=True)
        try:
            r = measure(policy, barriers, batch=args.batch, image=args.image,
                        classes=args.classes, dtype=args.dtype,
                        k_lo=args.k_lo, k_hi=args.k_hi)
        except Exception as e:  # noqa: BLE001 — a candidate failing is data
            r = {"error": f"{type(e).__name__}: {e}"}
        results.append({"candidate": name, **r})
    if not args.skip_flags:
        for name, flags in XLA_FLAG_CANDIDATES:
            print(f"[sweep] {name} ({flags}) ...", file=sys.stderr, flush=True)
            r = _run_flag_candidate(name, flags, args)
            results.append({"candidate": name, "xla_flags": flags, **r})

    ok = [r for r in results if "step_ms" in r]
    ok.sort(key=lambda r: r["step_ms"])
    base = next((r for r in ok if r["candidate"] == "baseline"), None)
    header = (f"fusion sweep: ResNet-50 B={args.batch} {args.image}px "
              f"{args.dtype} ({'TPU' if on_tpu else 'CPU'} backend, "
              f"two-point fit K={args.k_lo}/{args.k_hi}, median-of-3)")
    print(header)
    planes = {r.get("xplane_plane") for r in ok} - {None}
    xcol = ("xplane ms" if planes == {"device"}
            else "xplane ms (host thunk-exec)" if planes
            else "xplane ms")
    print(f"| candidate | step ms | img/s | vs baseline | noise | {xcol} |")
    print("|---|---|---|---|---|---|")
    for r in ok:
        rel = (f"{base['step_ms'] / r['step_ms']:.3f}x" if base else "—")
        xp = r["xplane_ms"] if r["xplane_ms"] is not None else "—"
        print(f"| {r['candidate']} | {r['step_ms']} | {r['img_per_sec']} "
              f"| {rel} | ±{100 * r['noise_frac']:.1f}% | {xp} |")
    for r in results:
        if "error" in r:
            print(f"| {r['candidate']} | INVALID: {r['error'][:90]} |")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"config": vars(args), "tpu": on_tpu,
                       "results": results}, f, indent=2)
        print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
