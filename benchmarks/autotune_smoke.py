"""CI smoke for the autotuning subsystem (ISSUE 11, docs/AUTOTUNE.md).

Runs on 8 virtual CPU devices and proves the machinery end-to-end, the
way the first real-TPU session will use it — nothing mocked, every
assertion on the real measurement/persistence/dispatch path:

1. **Cold sweep** (subprocess, ``benchmarks/autotune.py``): the conv tile
   space sweeps with a PLANTED-SLOW exact candidate (per-call sleep) and
   a PLANTED-WRONG tile candidate (outputs perturbed). Asserts the slow
   plant demonstrably LOSES (winner is a pallas tile), the wrong plant is
   REJECTED by the equivalence gate, and winners persist to the database.
2. **Deterministic DB**: a second cold sweep (same seed, fresh dir)
   produces the same key files, the same candidate-set digests, and the
   same winner impl.
3. **Warm process**: re-running the sweep against the populated database
   measures NOTHING (``tuning.measurements_total == 0``, every space
   ``warm``) and returns the identical winner — the cross-process
   contract.
4. **Trace-time dispatch**: with ``DL4J_TPU_TUNING_DB`` armed, in-process
   ``kernel_impl=auto`` conv resolution consults the database
   (``tuning.hits_total`` > 0), runs the tuned winner, and matches the
   exact path within the documented seam tolerance.

Exit 0 on success; any assertion failure exits non-zero (the CI legs in
.github/workflows/ci.yml + .github/ci_local.sh run this file directly).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AUTOTUNE = os.path.join(REPO, "benchmarks", "autotune.py")

# the planted-wrong label must name a real candidate of the default CPU
# conv contexts (oh=16 -> tiles 1,2,4,8 all enumerate)
PLANT_WRONG = "pallas:rt=1"


def run_sweep(db_dir, extra=()):
    env = dict(os.environ)
    env.pop("DL4J_TPU_TUNING_DB", None)   # --db is authoritative here
    cmd = [sys.executable, AUTOTUNE, "--db", db_dir,
           "--spaces", "conv2d_tiles", "--seed", "0",
           "--min-window", "0.02", "--json", *extra]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=600)
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr)
        raise AssertionError(f"autotune.py failed rc={proc.returncode}")
    line = [ln for ln in proc.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    return json.loads(line)


def key_files(db_dir):
    return sorted(f for f in os.listdir(db_dir) if f.endswith(".json"))


def main() -> int:
    work = tempfile.mkdtemp(prefix="dl4j-autotune-smoke.")
    db_a = os.path.join(work, "db_a")
    db_b = os.path.join(work, "db_b")
    plants = ["--plant-slow", "exact:0.03", "--plant-wrong", PLANT_WRONG]

    # -- 1. cold sweep with planted slow + planted wrong ------------------
    rep_a = run_sweep(db_a, plants)
    assert rep_a["spaces"], "no spaces swept"
    for row in rep_a["spaces"]:
        assert "error" not in row, row
        assert row["status"] == "measured", row
        # the planted-slow exact candidate must LOSE to a pallas tile
        assert row["winner"]["impl"] == "pallas", row["winner"]
        assert row["winner"]["label"] != "exact", row["winner"]
        # the planted-wrong tile must be rejected by the equivalence gate
        assert row["rejected"] >= 1, row
    c = rep_a["counters"]
    assert c.get("tuning.measurements_total", 0) > 0, c
    assert c.get("tuning.equivalence_rejects_total", 0) >= len(
        rep_a["spaces"]), c
    print(f"[1] cold sweep: {len(rep_a['spaces'])} contexts measured, "
          f"planted-slow lost, planted-wrong rejected "
          f"({c['tuning.measurements_total']:g} measurements, "
          f"{c['tuning.equivalence_rejects_total']:g} rejects)")

    # -- 2. deterministic database ---------------------------------------
    rep_b = run_sweep(db_b, plants)
    assert key_files(db_a) == key_files(db_b), (
        key_files(db_a), key_files(db_b))
    for ra, rb in zip(rep_a["spaces"], rep_b["spaces"]):
        assert ra["sig"] == rb["sig"]
        assert ra["winner"]["impl"] == rb["winner"]["impl"]
    digests_a = sorted(json.load(open(os.path.join(db_a, f)))
                       ["candidates_digest"] for f in key_files(db_a))
    digests_b = sorted(json.load(open(os.path.join(db_b, f)))
                       ["candidates_digest"] for f in key_files(db_b))
    assert digests_a == digests_b
    print(f"[2] deterministic DB: {len(key_files(db_a))} identical keys + "
          "candidate digests across independent cold sweeps")

    # -- 3. warm process measures nothing --------------------------------
    rep_w = run_sweep(db_a, plants)
    cw = rep_w["counters"]
    assert cw.get("tuning.measurements_total", 0) == 0, cw
    assert all(r["status"] == "warm" for r in rep_w["spaces"]), \
        [r["status"] for r in rep_w["spaces"]]
    assert cw.get("tuning.hits_total", 0) >= len(rep_w["spaces"]), cw
    for ra, rw in zip(rep_a["spaces"], rep_w["spaces"]):
        assert ra["winner"] == rw["winner"], (ra["winner"], rw["winner"])
    print(f"[3] warm process: 0 measurements, "
          f"{cw['tuning.hits_total']:g} database hits, winners identical")

    # -- 4. trace-time auto dispatch consults the database ----------------
    os.environ["DL4J_TPU_TUNING_DB"] = db_a
    import jax

    # force CPU like the sibling smokes: the env var alone does not win
    # over this image's preset platform (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu import tuning
    from deeplearning4j_tpu.ops import kernels as K
    from deeplearning4j_tpu.ops import nn as nnops
    from deeplearning4j_tpu.util import telemetry as tm

    rng = np.random.default_rng(0)
    # the first default CPU conv context's geometry (tuning/space.py)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(3, 3, 8, 16)) * 0.1, jnp.float32)
    tele = tm.get_telemetry()
    h0 = tele.counters.get(("tuning.hits_total", ()), 0)
    out = nnops.conv2d(x, w)                      # kernel_impl=auto
    h1 = tele.counters.get(("tuning.hits_total", ()), 0)
    assert h1 > h0, (h0, h1)
    with K.impl_scope("exact"):
        exact = nnops.conv2d(x, w)
    err = float(jnp.max(jnp.abs(out - exact)))
    assert err < 2e-4, err
    status = tuning.current_status()
    assert status["entries"] >= 2, status
    print(f"[4] auto dispatch: resolved through the DB "
          f"(hits {h0:g}->{h1:g}), tuned output matches exact "
          f"(max diff {err:.2e}); /healthz section: "
          f"{status['entries']} entries")

    shutil.rmtree(work, ignore_errors=True)
    print("autotune smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
