"""CI smoke for the pipeline-parallel fit() (ISSUE 14).

Runs on 8 virtual CPU devices and asserts the acceptance contracts CPU can
honestly prove about the 3D (data x tensor x pipe) trainer
(docs/DISTRIBUTED.md#pipeline-parallelism):

1. **Memory: a model too big for one device's budget trains.** On the
   (data=2, model=2, pipe=2) mesh, a stage-dominated net whose replicated
   param+optimizer footprint EXCEEDS a declared per-device budget places
   under it — bytes/device ≈ 1/pipe_stages of the replicated footprint
   (stage params P('pipe'), moments ZeRO over 'data') — and fit() runs.
2. **Trajectory equivalence.** The (2,2,2) 8-device pipelined fit tracks
   the plain unpipelined single-device fit (allclose); the same pipelined
   program on (data=1, pipe=2) reproduces the 8-device fit BIT-identically
   (params + Adam moments + RNG key) — the r12 lane contract with the
   pipe placement fixed.
3. **Composition.** grad_compression threshold→0 on the pipelined step is
   bit-identical to the uncompressed pipelined fit (ZeRO default-on under
   both); an active threshold ships encoded wire bytes.
4. **Schedule accounting.** `pipeline_bubble_fraction` equals the GPipe
   fill-drain expression (S-1)/(n_micro+S-1) and is published as a gauge
   (computed from the schedule, never timed — the r6 CPU honesty rule).

Exit 0 on success; any assertion failure exits non-zero (the CI legs in
.github/workflows/ci.yml + .github/ci_local.sh run this file directly).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data import DataSet  # noqa: E402
from deeplearning4j_tpu.nn import (  # noqa: E402
    InputType, MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.parallel import (  # noqa: E402
    PipelinedTrainer, TrainingMesh, gspmd)
from deeplearning4j_tpu.parallel.pipeline import bubble_fraction  # noqa: E402
from deeplearning4j_tpu.util import telemetry as tm  # noqa: E402

STAGES, N_MICRO = 2, 4


def _net(width=16, comp=None, threshold=1e-3):
    b = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
         .pipe_stages(STAGES).n_micro(N_MICRO))
    if comp:
        b = b.grad_compression(comp, threshold=threshold)
    conf = (b.list()
            .layer(DenseLayer(n_in=8, n_out=width, activation="relu"))
            .stage_boundary()
            .layer(DenseLayer(n_in=width, n_out=width, activation="tanh"))
            .layer(DenseLayer(n_in=width, n_out=width, activation="relu"))
            .stage_boundary()
            .layer(DenseLayer(n_in=width, n_out=width, activation="tanh"))
            .layer(DenseLayer(n_in=width, n_out=width, activation="relu"))
            .stage_boundary()
            .layer(OutputLayer(n_in=width, n_out=4, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=16):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, 8)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return DataSet(xs, ys)


def _leaves(t):
    return [np.asarray(jax.device_get(l))
            for l in jax.tree_util.tree_leaves(t)]


def check_memory_budget():
    """A stage-dominated net whose replicated footprint busts a declared
    per-device budget fits (and trains) once pipelined."""
    net = _net(width=512)  # stage params dominate: 4 x 512x512 fp32
    ds = _data()
    pt = PipelinedTrainer(net, mesh=TrainingMesh(data=2, model=2, pipe=2),
                          replicas=2, skew_every=0)
    pt._build()
    replicated = (gspmd.tree_bytes(net.params)
                  + gspmd.tree_bytes(net.opt_states))
    per_dev = pt.train_state_bytes_per_device()
    budget = int(replicated * 0.75)  # one "device" cannot hold the model
    assert replicated > budget, "net too small for the budget story"
    assert per_dev < budget, (per_dev, budget)
    ratio = per_dev / replicated
    assert ratio < 1.0 / STAGES + 0.12, \
        f"bytes/device ratio {ratio:.3f} not ~1/{STAGES}"
    pt.step_batch(ds)  # and it trains
    assert np.isfinite(float(net.score_value))
    print(f"PASS memory: replicated {replicated} B > budget {budget} B; "
          f"per-device {per_dev} B (ratio {ratio:.3f} ≈ 1/{STAGES}), "
          f"fit() ran")
    return per_dev, replicated


def check_trajectory_and_bit_identity():
    ds = _data()
    ref = _net()
    for _ in range(4):
        ref._fit_batch(ds.features, ds.labels)
    n8 = _net()
    p8 = PipelinedTrainer(n8, mesh=TrainingMesh(data=2, model=2, pipe=2),
                          replicas=2, skew_every=0)
    for _ in range(4):
        p8.step_batch(ds)
    p8.sync_model()
    for a, b in zip(_leaves(n8.params), _leaves(ref.params)):
        np.testing.assert_allclose(a, b, atol=5e-6, rtol=5e-6)
    n1 = _net()
    p1 = PipelinedTrainer(
        n1, mesh=TrainingMesh(data=1, model=2, pipe=2,
                              devices=jax.devices()[:4]),
        replicas=2, skew_every=0)
    for _ in range(4):
        p1.step_batch(ds)
    p1.sync_model()
    for a, b in zip(_leaves(n8.params), _leaves(n1.params)):
        assert np.array_equal(a, b), "data-fold bit-identity broke"
    for a, b in zip(_leaves(n8.opt_states), _leaves(n1.opt_states)):
        assert np.array_equal(a, b), "opt-state bit-identity broke"
    assert np.array_equal(np.asarray(n8._rng_key), np.asarray(n1._rng_key))
    print("PASS trajectory: (2,2,2) fit ~ unpipelined (5e-6) and "
          "bit-identical to the (1,2,2) fold (params+moments+RNG)")


def check_compression_composition():
    ds = _data()
    mesh = lambda: TrainingMesh(data=2, model=2, pipe=2)  # noqa: E731
    nc = _net(comp="threshold", threshold=0.0)
    pc = PipelinedTrainer(nc, mesh=mesh(), replicas=2, skew_every=0)
    nu = _net()
    pu = PipelinedTrainer(nu, mesh=mesh(), replicas=2, skew_every=0)
    for _ in range(3):
        pc.step_batch(ds)
        pu.step_batch(ds)
    pc.sync_model()
    pu.sync_model()
    for a, b in zip(_leaves(nc.params), _leaves(nu.params)):
        assert np.array_equal(a, b), "t->0 compression identity broke"
    na = _net(comp="threshold", threshold=1e-3)
    pa = PipelinedTrainer(na, mesh=mesh(), replicas=2, skew_every=0)
    for _ in range(4):
        pa.step_batch(ds)
    stats = pa.compression_stats()
    assert stats["wire_bytes"] > 0, stats
    print(f"PASS composition: t->0 bit-identical under ZeRO; active "
          f"threshold ships {stats['wire_bytes']:.0f} wire bytes "
          f"(ratio {stats['ratio']:.3f})")


def check_bubble_fraction():
    expected = (STAGES - 1) / (N_MICRO + STAGES - 1)
    net = _net()
    pt = PipelinedTrainer(net, mesh=TrainingMesh(data=2, model=2, pipe=2),
                          replicas=2, skew_every=0)
    pt._build()
    assert abs(pt.bubble_fraction - expected) < 1e-12
    assert abs(bubble_fraction(STAGES, N_MICRO) - expected) < 1e-12
    gauges = tm.get_telemetry().gauges
    val = next((v for (name, _), v in gauges.items()
                if name == "parallel.pipeline_bubble_fraction"), None)
    assert val is not None and abs(val - expected) < 1e-12, val
    print(f"PASS schedule: bubble fraction {expected:.4f} = "
          f"(S-1)/(n_micro+S-1) published as a gauge (computed, not timed)")
    return expected


def main():
    assert len(jax.devices()) >= 8, jax.devices()
    check_memory_budget()
    check_trajectory_and_bit_identity()
    check_compression_composition()
    check_bubble_fraction()
    print("pipeline smoke: ALL PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
