"""Serving-resilience smoke check (the ISSUE 13 CI leg, wired in
ci.yml/ci_local.sh).

End-to-end proof of the serving resilience layer (docs/SERVING.md#resilience)
on a real HTTP server, applying the r11 standard — every fault kind's
specific recovery asserted in CI — to the serving path:

1. **rolling reload storm**: N=5 weight reloads through the admin verb
   (``POST /v1/models/<id>/reload``) while CONCURRENT mixed classify+generate
   traffic runs from worker threads — every traffic request answers 200
   (zero shed), the steady-state ``serving.recompiles_total`` delta is
   exactly 0 (shadow warmup compiles on the reload thread, never the
   worker's tally), the version surface advances 2→6 on ``/v1/models``, and
   the post-storm weights are BIT-identical to the last archive's direct
   forward;
2. **corrupt-archive rejection**: a truncated archive answers 409 (never a
   5xx — the tier is healthy) while the old version keeps answering
   bit-identically, and the ``reload_corrupt_archive`` fault kind fires the
   same truncated-zip mechanism on a GOOD archive — rejected once, then the
   same archive reloads clean;
3. **supervised worker**: ``serving_worker_crash`` kills the scheduler loop
   mid-batch — the rider gets a loud 500, the flight recorder records the
   ``worker_crash`` cause, ``serving.worker_restarts_total`` increments, and
   the restarted worker answers the next request 200;
4. **circuit breaker**: ``serving_compute_error`` fails consecutive batches
   — the breaker OPENS (fast-fail 503 + Retry-After instead of queueing
   doomed work), the cooldown admits a half-open probe whose success CLOSES
   it, and ``/metrics`` carries the breaker state gauge;
5. **slow batch**: ``serving_slow_batch`` wedges the worker on a real stall
   — a request whose deadline expires queued behind it sheds 429, the
   stalled batch itself completes 200;
6. **brownout**: a synthetic SLO budget exhaustion sheds the ``batch`` lane
   (429) while ``interactive`` keeps serving, and budget recovery restores
   full service;
7. graceful drain stays clean after all of it.

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/resilience_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []

N_RELOADS = 5


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def http_get(url: str, use_curl: bool):
    if use_curl and shutil.which("curl"):
        out = subprocess.run(
            ["curl", "-sS", "-w", "\n%{http_code}", url],
            capture_output=True, text=True, timeout=30)
        body, _, code = out.stdout.rpartition("\n")
        if not code.strip().isdigit():
            return 0, f"curl failed: {out.stderr.strip()}"
        return int(code), body
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def http_post(url: str, obj: dict):
    """(status, json body, headers) for a JSON POST."""
    data = json.dumps(obj).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        r = urllib.request.urlopen(req, timeout=120)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {}
        return e.code, body, dict(e.headers)


def build_dense_net(seed: int):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .batch_buckets((1, 2, 4, 8)).list()
            .layer(DenseLayer(n_in=12, n_out=32, activation="relu"))
            .layer(OutputLayer(n_in=32, n_out=5, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def build_server():
    import numpy as np

    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving import (ModelRouter, ModelServer,
                                            ServingModel)
    from deeplearning4j_tpu.zoo.bert import Bert

    clf_net = build_dense_net(seed=0)
    bert = Bert.tiny(causal=True, task="mlm", vocab_size=48, max_length=32,
                     hidden_dropout=0.0).init()
    router = ModelRouter(name="resilience-smoke")
    router.register(ServingModel(clf_net, "dense"), max_wait_ms=1.0,
                    queue_limit=256)
    router.register(
        ServingModel(bert, "bert-decode", kind="generate",
                     bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                               seq_buckets=(8,))),
        max_wait_ms=1.0, queue_limit=256)
    server = ModelServer(router, port=0).start()  # warms every bucket
    return server, router, np


def traffic_loop(server, np, stop, results):
    """One closed-loop traffic worker: alternating classify (interactive)
    and generate (batch) requests until ``stop``; every (status, body)
    lands in ``results``."""
    rng = np.random.default_rng(os.getpid() ^ threading.get_ident())
    i = 0
    while not stop.is_set():
        if i % 4 == 3:
            code, body, _ = http_post(
                f"{server.url}/v1/models/bert-decode/generate",
                {"prompt_tokens": [list(map(int, rng.integers(1, 48, 5)))],
                 "max_new_tokens": 3, "lane": "batch"})
        else:
            code, body, _ = http_post(
                f"{server.url}/v1/models/dense/infer",
                {"inputs": rng.normal(size=(2, 12)).astype(
                    np.float32).tolist(), "lane": "interactive"})
        results.append((code, body))
        i += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--no-curl", action="store_true")
    args = ap.parse_args(argv)
    use_curl = not args.no_curl

    server, router, np = build_server()
    from deeplearning4j_tpu.serving import BrownoutController
    from deeplearning4j_tpu.util import faults as fl
    from deeplearning4j_tpu.util import slo
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.util.faults import get_injector
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    tele = tm.get_telemetry()
    injector = get_injector()
    injector.clear()  # a stray DL4J_TPU_FAULTS must not skew the checks
    tmpdir = tempfile.mkdtemp(prefix="resilience-smoke-")
    model, sched = router.get("dense")

    def counter(name, **labels):
        return tele.counter_total(name, **labels)

    try:
        # ------------------------------------------------ 1. reload storm
        print(f"== reload storm: {N_RELOADS} rolling reloads under "
              "sustained mixed traffic ==")
        nets = [build_dense_net(seed=i) for i in range(1, N_RELOADS + 1)]
        paths = []
        for i, net in enumerate(nets):
            p = os.path.join(tmpdir, f"v{i + 2}.zip")
            ModelSerializer.write_model(net, p, save_updater=False)
            paths.append(p)
        stop, results = threading.Event(), []
        threads = [threading.Thread(
            target=traffic_loop, args=(server, np, stop, results))
            for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # traffic flowing before the first swap
        rec0 = counter("serving.recompiles_total", model="dense")
        versions = []
        for p in paths:
            code, body, _ = http_post(
                f"{server.url}/v1/models/dense/reload", {"path": p})
            if code == 200:
                versions.append(body.get("version"))
        time.sleep(0.3)  # traffic across the last swap too
        stop.set()
        for t in threads:
            t.join(timeout=60)
        check(f"all {N_RELOADS} reloads accepted, versions advance",
              versions == list(range(2, N_RELOADS + 2)), str(versions))
        bad = [(c, b) for c, b in results if c != 200]
        check(f"zero shed requests across the storm "
              f"({len(results)} requests)", not bad, str(bad[:3]))
        check("zero steady-state recompiles across the storm",
              counter("serving.recompiles_total", model="dense") - rec0 == 0,
              f"delta {counter('serving.recompiles_total', model='dense') - rec0}")
        x = np.asarray([[0.1] * 12, [-0.2] * 12], np.float32)
        code, body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                  {"inputs": x.tolist()})
        direct = np.asarray(nets[-1].output(x))
        check("post-storm weights bit-identical to the last archive",
              code == 200 and np.array_equal(
                  np.asarray(body["outputs"], np.float32),
                  direct.astype(np.float32)))
        code, text = http_get(f"{server.url}/v1/models", use_curl)
        doc = json.loads(text) if code == 200 else {}
        surfaced = doc.get("models", {}).get("dense", {}).get("version")
        check("version surface advanced on /v1/models",
              surfaced == N_RELOADS + 1, f"version {surfaced}")

        # ------------------------------------- 2. corrupt-archive reload
        print("== corrupt-archive rejection (reload_corrupt_archive) ==")
        data = open(paths[-1], "rb").read()
        trunc = os.path.join(tmpdir, "trunc.zip")
        open(trunc, "wb").write(data[: len(data) // 2])
        code, body, _ = http_post(
            f"{server.url}/v1/models/dense/reload", {"path": trunc})
        check("truncated archive answers 409 (never a 5xx)",
              code == 409 and body.get("error") == "ModelLoadError",
              f"code {code}, error {body.get('error')}")
        code, body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                  {"inputs": x.tolist()})
        check("old version keeps answering bit-identically after the 409",
              code == 200 and np.array_equal(
                  np.asarray(body["outputs"], np.float32),
                  direct.astype(np.float32)))
        check("model version unchanged by the rejected reload",
              model.version == N_RELOADS + 1, f"version {model.version}")
        # the injected fault: the SAME truncated-zip mechanism fired on a
        # GOOD archive (fault kind recovery, r11 standard)
        injector.inject(fl.RELOAD_CORRUPT_ARCHIVE)
        code, _body, _ = http_post(
            f"{server.url}/v1/models/dense/reload", {"path": paths[-1]})
        check("reload_corrupt_archive fault rejects a good archive (409)",
              code == 409, f"code {code}")
        code, body, _ = http_post(
            f"{server.url}/v1/models/dense/reload", {"path": paths[-1]})
        check("fault disarmed: the same archive then reloads clean",
              code == 200 and body.get("version") == N_RELOADS + 2,
              f"code {code}, version {body.get('version')}")

        # ------------------------------------------- 3. supervised worker
        print("== supervised worker (serving_worker_crash) ==")
        restarts0 = counter("serving.worker_restarts_total", model="dense")
        injector.inject(fl.SERVING_WORKER_CRASH, count=1)
        code, body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                  {"inputs": x.tolist()})
        check("crashed batch's rider gets a loud 500",
              code == 500 and "WorkerCrashedError" in str(body.get("error")),
              f"code {code}, body {body}")
        check("worker restart counted",
              counter("serving.worker_restarts_total",
                      model="dense") == restarts0 + 1)
        code, text = http_get(
            f"{server.url}/v1/models/dense/debug/requests?last=16", use_curl)
        recs = json.loads(text).get("requests", []) if code == 200 else []
        check("flight recorder carries the worker_crash cause",
              any(r.get("status") == "error"
                  and str(r.get("cause", "")).startswith("worker_crash")
                  for r in recs))
        code, body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                  {"inputs": x.tolist()})
        check("restarted worker answers the next request 200",
              code == 200 and np.array_equal(
                  np.asarray(body["outputs"], np.float32),
                  direct.astype(np.float32)))
        check("worker health check stays OK within the restart budget",
              tele.health_report()[1].get(
                  "serving.worker.dense", {}).get("ok") is not False)

        # --------------------------------------------- 4. circuit breaker
        print("== circuit breaker (serving_compute_error) ==")
        sched.breaker.consecutive_errors = 2
        sched.breaker.cooldown_s = 1.0
        opens0 = counter("serving.breaker_opens_total", model="dense")
        injector.inject(fl.SERVING_COMPUTE_ERROR, count=2)
        codes = [http_post(f"{server.url}/v1/models/dense/infer",
                           {"inputs": x.tolist()})[0] for _ in range(2)]
        check("injected compute errors answer 500", codes == [500, 500],
              str(codes))
        check("breaker opens after consecutive errors",
              sched.breaker.state == "open"
              and counter("serving.breaker_opens_total",
                          model="dense") == opens0 + 1,
              f"state {sched.breaker.state}")
        code, body, hdrs = http_post(
            f"{server.url}/v1/models/dense/infer", {"inputs": x.tolist()})
        check("open breaker fast-fails 503 + Retry-After",
              code == 503 and hdrs.get("Retry-After") is not None
              and "CircuitOpenError" in str(body.get("error")),
              f"code {code}, retry {hdrs.get('Retry-After')}")
        code, text = http_get(f"{server.url}/metrics", use_curl)
        check("/metrics carries the breaker state gauge (open=2)",
              'dl4j_serving_breaker_state{model="dense"} 2' in text)
        time.sleep(1.2)  # cooldown -> half-open probe admitted
        code, body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                  {"inputs": x.tolist()})
        check("half-open probe succeeds (200)", code == 200)
        deadline = time.time() + 10
        while sched.breaker.state != "closed" and time.time() < deadline:
            time.sleep(0.05)
        check("probe success closes the breaker",
              sched.breaker.state == "closed",
              f"state {sched.breaker.state}")

        # ------------------------------------------------- 5. slow batch
        print("== slow batch (serving_slow_batch) ==")
        shed0 = counter("serving.shed_total", model="dense",
                        reason="deadline", lane="interactive")
        injector.inject(fl.SERVING_SLOW_BATCH, arg=500.0)
        slow_result = {}

        def slow_req():
            slow_result["r"] = http_post(
                f"{server.url}/v1/models/dense/infer",
                {"inputs": x.tolist()})

        t = threading.Thread(target=slow_req)
        t.start()
        time.sleep(0.15)  # the stalled batch is open on the worker
        code, _body, hdrs = http_post(
            f"{server.url}/v1/models/dense/infer",
            {"inputs": x.tolist(), "deadline_ms": 100})
        t.join(timeout=30)
        check("deadline expires behind the stalled batch -> 429",
              code == 429 and hdrs.get("Retry-After") is not None,
              f"code {code}")
        check("deadline shed counted",
              counter("serving.shed_total", model="dense",
                      reason="deadline", lane="interactive") > shed0)
        check("the stalled batch itself completes 200 (slow, not broken)",
              slow_result.get("r", (0,))[0] == 200)

        # --------------------------------------------------- 6. brownout
        print("== brownout (SLO budget exhaustion) ==")
        ctrl = BrownoutController(router).install()
        slo.register(slo.SloObjective(
            "smoke-brownout", "availability", target=0.999,
            model="synthetic-resilience", windows=(3.0,)))
        tm.counter("serving.completed_total", 1,
                   model="synthetic-resilience", lane="interactive")
        slo.get_engine().evaluate()
        tm.counter("serving.shed_total", 9, model="synthetic-resilience",
                   reason="deadline", lane="interactive")
        slo.get_engine().evaluate()
        check("budget exhaustion activates the brownout", ctrl.active)
        code, _body, _ = http_post(
            f"{server.url}/v1/models/bert-decode/generate",
            {"prompt_tokens": [[1, 2, 3]], "max_new_tokens": 2,
             "lane": "batch"})
        check("batch lane sheds 429 during brownout", code == 429,
              f"code {code}")
        code, _body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                   {"inputs": x.tolist(),
                                    "lane": "interactive"})
        check("interactive lane keeps serving during brownout", code == 200,
              f"code {code}")
        deadline = time.time() + 30
        while ctrl.active and time.time() < deadline:
            time.sleep(0.25)  # bad traffic ages out of the 3s window
            slo.get_engine().evaluate()
        check("budget recovery ends the brownout", not ctrl.active)
        code, _body, _ = http_post(
            f"{server.url}/v1/models/bert-decode/generate",
            {"prompt_tokens": [[1, 2, 3]], "max_new_tokens": 2,
             "lane": "batch"})
        check("batch lane restored after recovery", code == 200,
              f"code {code}")
        ctrl.uninstall()  # detach from the process SLO engine
        slo.reset()

        # --------------------------------------------------------- drain
        print("== graceful drain ==")
        server.request_drain()
        check("server drains clean after the chaos",
              server.wait_drained(timeout=60))
        code, _body, _ = http_post(f"{server.url}/v1/models/dense/infer",
                                   {"inputs": x.tolist()})
        check("post-drain request answers 503", code == 503, f"code {code}")
    finally:
        injector.clear()
        server.stop()
        shutil.rmtree(tmpdir, ignore_errors=True)

    if _FAILED:
        print(f"RESILIENCE SMOKE FAIL: {len(_FAILED)} checks failed: "
              f"{_FAILED}")
        return 1
    print("resilience smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
