"""Fleet smoke check (the ISSUE 18 CI leg, wired in ci.yml/ci_local.sh).

End-to-end proof of the disaggregated-serving acceptance criteria against
a REAL 2-worker fleet — spawned worker processes, real HTTP through the
front tier (docs/SERVING.md#fleet):

1. boot a :class:`FleetRouter` over 2 workers each serving a dense
   classifier + a causal BERT-tiny prefix-cached decoder from the SAME
   ModelSerializer archives a single-process oracle server loads; fire
   mixed classify+generate traffic and assert every response is 200 and
   token-identical (generate) / output-identical (classify) to the
   oracle;
2. assert 0 steady-state recompiles per worker (each worker's
   ``xla_backend_compiles_total`` is flat across a warm burst) —
   compile-once serving survives disaggregation;
3. prefix affinity: shared-prefix generate streams concentrate on one
   worker per prefix (``routing_decisions_total{reason="affinity"}``
   dominates), and the warm per-worker ``prefix_cache_hit_rate`` is ≥
   the single-process oracle's rate (affinity kept the radix caches as
   warm as one process would) — both scraped from the fleet ``/metrics``
   fan-in with ``worker`` labels;
4. SIGKILL one worker mid-burst: every request completes after at most
   one client retry (zero request loss), the dead worker respawns under
   backoff and re-enters the ring;
5. fleet-wide rolling reload under live traffic: zero non-200 during the
   roll, every worker's version advances monotonically, post-reload
   outputs match the NEW oracle.

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/fleet_smoke.py
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def post(port, path, body, headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        raw = json.dumps(body).encode()
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", path, body=raw, headers=hdrs)
        r = conn.getresponse()
        data = r.read()
        return r.status, json.loads(data) if data else {}, dict(r.getheaders())
    finally:
        conn.close()


def post_retry(port, path, body, attempts=3, timeout=60):
    """Client-side retry on transport errors and 5xx — the 'zero request
    loss after retry' contract while a worker is being killed."""
    last = None
    for i in range(attempts):
        try:
            st, doc, hdrs = post(port, path, body, timeout=timeout)
            if st == 200:
                return st, doc, hdrs, i
            last = (st, doc, hdrs)
        except OSError as e:
            last = (0, {"error": repr(e)}, {})
        time.sleep(0.3 * (i + 1))
    return last[0], last[1], last[2], attempts


def get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read().decode()
    finally:
        conn.close()


def scrape_series(text: str, name: str, **labels) -> float:
    """Sum of every series `name{...}` whose labels include `labels`
    (telemetry prefixes every exported metric with ``dl4j_``)."""
    if not name.startswith("dl4j_"):
        name = "dl4j_" + name
    total, found = 0.0, False
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in ("{", " "):
            continue  # a longer metric name sharing the prefix
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
            found = True
    return total if found else float("nan")


def build_archives(tmp):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    from deeplearning4j_tpu.zoo.bert import Bert

    def dense(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(1e-3)).batch_buckets((1, 2, 4, 8)).list()
                .layer(DenseLayer(n_in=12, n_out=32, activation="relu"))
                .layer(OutputLayer(n_in=32, n_out=5, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(12)).build())
        return MultiLayerNetwork(conf).init()

    clf = dense(0)
    clf_path = os.path.join(tmp, "clf.zip")
    ModelSerializer.write_model(clf, clf_path, save_updater=False)
    bert = Bert.tiny(causal=True, task="mlm", vocab_size=48,
                     max_length=32, hidden_dropout=0.0).init()
    gen_path = os.path.join(tmp, "gen.zip")
    ModelSerializer.write_model(bert, gen_path, save_updater=False)
    return clf_path, gen_path, dense


GEN_KW = {"bucketing": {"batch_buckets": [1, 2, 4], "seq_buckets": [8]},
          "prefix_cache": True, "block_size": 4}
REG = {"max_wait_ms": 1.0, "queue_limit": 256}


def build_oracle(clf_path, gen_path):
    """The single-process oracle: the SAME archives behind one
    ModelServer — the fleet must be indistinguishable from it."""
    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving import ModelRouter, ModelServer

    router = ModelRouter(name="fleet-oracle")
    router.load("clf", clf_path, kind="classify")
    from deeplearning4j_tpu.serving.model import ServingModel
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    gen_net = ModelSerializer.restore_model(gen_path, load_updater=False)
    router.register(
        ServingModel(gen_net, "gen", kind="generate",
                     bucketing=BucketingPolicy(batch_buckets=(1, 2, 4),
                                               seq_buckets=(8,)),
                     prefix_cache=True, block_size=4), **REG)
    return ModelServer(router, port=0).start(warmup=True)


def prefix_prompts():
    """4 shared-prefix groups × 6 requests: 8-token shared head (2 radix
    blocks at block_size=4) + distinct 4-token tails."""
    groups = []
    for g in range(4):
        head = [(7 * g + k) % 40 + 1 for k in range(8)]
        groups.append([head + [(g + 11 * t + j) % 40 + 1 for j in range(4)]
                       for t in range(6)])
    return groups


def main() -> int:
    import tempfile

    import numpy as np

    t_start = time.time()
    tmp = tempfile.mkdtemp(prefix="fleet_smoke_")
    print("== fleet smoke: building archives + single-process oracle ==")
    clf_path, gen_path, dense = build_archives(tmp)
    oracle = build_oracle(clf_path, gen_path)

    from deeplearning4j_tpu.serving.fleet import FleetRouter, fleet_spec

    spec = fleet_spec(
        models=[
            {"id": "clf", "path": clf_path, "kind": "classify",
             "register": dict(REG)},
            {"id": "gen", "path": gen_path, "kind": "generate",
             "register": dict(REG), "model_kw": dict(GEN_KW)},
        ],
        env={"JAX_PLATFORMS": "cpu"})
    print("== booting 2-worker fleet ==")
    fleet = FleetRouter(spec, n_workers=2, affinity_head=8,
                        health_interval_s=0.2, name="smokefleet").start()
    print(f"   fleet up at {fleet.url} "
          f"({time.time() - t_start:.0f}s)")
    try:
        rng = np.random.RandomState(0)
        xs = [rng.normal(size=(n, 12)).astype(np.float32)
              for n in (1, 2, 4, 3)]
        groups = prefix_prompts()

        # ---- leg 1: mixed traffic, token-identical to the oracle ------
        print("== leg 1: mixed classify+generate vs oracle ==")
        statuses, mismatches = [], 0
        lock = threading.Lock()

        def one_classify(x):
            try:
                st_f, doc_f, _h = post(fleet.port, "/v1/models/clf/infer",
                                       {"inputs": x.tolist()})
                st_o, doc_o, _h = post(oracle.port, "/v1/models/clf/infer",
                                       {"inputs": x.tolist()})
            except OSError as e:
                with lock:
                    statuses.append((f"conn:{type(e).__name__}", 0))
                return
            with lock:
                statuses.append((st_f, st_o))
                if st_f == st_o == 200 and not np.allclose(
                        doc_f["outputs"], doc_o["outputs"], atol=1e-6):
                    nonlocal_mismatch()

        def one_generate(p):
            body = {"prompt_tokens": p, "max_new_tokens": 4}
            try:
                st_f, doc_f, _h = post(fleet.port,
                                       "/v1/models/gen/generate", body)
                st_o, doc_o, _h = post(oracle.port,
                                       "/v1/models/gen/generate", body)
            except OSError as e:
                with lock:
                    statuses.append((f"conn:{type(e).__name__}", 0))
                return
            with lock:
                statuses.append((st_f, st_o))
                if st_f == st_o == 200 and \
                        doc_f["tokens"] != doc_o["tokens"]:
                    nonlocal_mismatch()

        def nonlocal_mismatch():
            nonlocal mismatches
            mismatches += 1

        # classify concurrently (burst coverage); generate serially so the
        # radix-cache fill order is deterministic on both fleet and oracle
        threads = []
        for rep in range(3):
            for x in xs:
                threads.append(threading.Thread(target=one_classify,
                                                args=(x,)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for grp in groups:
            for p in grp:
                one_generate(p)
        n_req = len(threads) + sum(len(g) for g in groups)
        bad = [s for s in statuses if s != (200, 200)]
        all_200 = len(statuses) == n_req and not bad
        check("mixed traffic all-200s", all_200,
              f"{len(statuses)}/{n_req} pairs, non-200={bad[:5]}")
        check("fleet token/output-identical to single-process oracle",
              mismatches == 0, f"{mismatches} mismatches over {n_req}")

        # ---- leg 2: zero steady-state recompiles per worker -----------
        print("== leg 2: steady-state recompiles ==")
        def worker_compiles():
            out = {}
            for w in fleet.workers:
                st, text = get(w.port, "/metrics")
                out[w.worker_id] = scrape_series(
                    text, "xla_backend_compiles_total")
            return out

        def warm_burst():
            for x in xs:
                post(fleet.port, "/v1/models/clf/infer",
                     {"inputs": x.tolist()})
            for grp in groups:
                post(fleet.port, "/v1/models/gen/generate",
                     {"prompt_tokens": grp[0], "max_new_tokens": 4})

        warm_burst()  # prime: every worker has now traced these shapes
        before = worker_compiles()
        warm_burst()  # steady state: the identical burst must not trace
        after = worker_compiles()
        deltas = {w: after[w] - before[w] for w in before}
        check("0 steady-state recompiles per worker",
              all(d == 0 for d in deltas.values()), f"deltas={deltas}")

        # ---- leg 3: prefix affinity concentrates shared prefixes ------
        print("== leg 3: prefix-affinity hit rate ==")
        st, text = get(fleet.port, "/metrics")
        aff = scrape_series(text, "serving_fleet_routing_decisions_total",
                            reason="affinity")
        check("affinity routing decisions scraped > 0", aff > 0,
              f"affinity={aff:.0f}")
        worker_rates = []
        for w in fleet.workers:
            r = scrape_series(text, "serving_prefix_cache_hit_rate",
                              worker=w.worker_id, model="gen")
            if r == r:  # not NaN: this worker served generate traffic
                worker_rates.append((w.worker_id, r))
        st_o, text_o = get(oracle.port, "/metrics")
        oracle_rate = scrape_series(text_o, "serving_prefix_cache_hit_rate",
                                    model="gen")
        check("per-worker prefix hit rate scraped > 0",
              bool(worker_rates) and all(r > 0 for _w, r in worker_rates),
              f"workers={worker_rates}")
        best = max((r for _w, r in worker_rates), default=0.0)
        check("warm per-worker hit rate >= single-process oracle",
              best >= oracle_rate - 1e-6,
              f"best_worker={best:.3f} oracle={oracle_rate:.3f}")

        # ---- leg 4: SIGKILL a worker mid-burst ------------------------
        print("== leg 4: SIGKILL one worker mid-burst ==")
        results = []

        def burst_one(i):
            x = xs[i % len(xs)]
            st, _doc, _h, retries = post_retry(
                fleet.port, "/v1/models/clf/infer",
                {"inputs": x.tolist()})
            with lock:
                results.append((st, retries))

        victim = fleet._ring()[0]
        burst = [threading.Thread(target=burst_one, args=(i,))
                 for i in range(24)]
        for i, t in enumerate(burst):
            t.start()
            if i == 4:
                os.kill(victim.pid, 9)  # SIGKILL mid-burst
        for t in burst:
            t.join()
        lost = [st for st, _r in results if st != 200]
        check("zero request loss after retry through the kill",
              not lost, f"{len(results)} requests, failures={lost}")
        deadline = time.time() + 120
        while len(fleet._ring()) < 2 and time.time() < deadline:
            time.sleep(0.25)
        check("killed worker respawned and re-entered the ring",
              len(fleet._ring()) == 2,
              f"ring={sorted(w.worker_id for w in fleet._ring())} "
              f"restarts={fleet.worker(victim.worker_id).restarts}")

        # ---- leg 5: rolling reload under live traffic -----------------
        print("== leg 5: rolling reload under load ==")
        clf2 = dense(7)
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        clf2_path = os.path.join(tmp, "clf2.zip")
        ModelSerializer.write_model(clf2, clf2_path, save_updater=False)
        stop_evt = threading.Event()
        shed_during_roll = []

        def load_traffic():
            while not stop_evt.is_set():
                try:
                    st, _d, _h = post(fleet.port, "/v1/models/clf/infer",
                                      {"inputs": xs[0].tolist()})
                except OSError as e:
                    st = f"conn:{type(e).__name__}"
                if st != 200:
                    shed_during_roll.append(st)

        feeders = [threading.Thread(target=load_traffic) for _ in range(3)]
        for t in feeders:
            t.start()
        time.sleep(0.3)
        try:
            st, doc, _h = post(fleet.port, "/v1/models/clf/reload",
                               {"path": clf2_path}, timeout=300)
        finally:
            stop_evt.set()
            for t in feeders:
                t.join(timeout=30)
        versions = doc.get("versions", {})
        check("rolling reload returned 200 with every worker swapped",
              st == 200 and sorted(versions) == ["w0", "w1"],
              f"status={st} versions={versions}")
        check("versions advanced monotonically",
              all(v >= 2 for v in versions.values()), f"{versions}")
        check("zero fleet-level shed during the roll",
              not shed_during_roll, f"non-200s={shed_during_roll[:5]}")
        x0 = xs[0]
        st, doc, _h = post(fleet.port, "/v1/models/clf/infer",
                           {"inputs": x0.tolist()})
        oracle2 = np.asarray(clf2.output(x0))
        check("post-reload outputs match the NEW oracle",
              st == 200 and np.allclose(doc["outputs"], oracle2,
                                        atol=1e-6))
    finally:
        fleet.stop()
        oracle.stop()

    print(f"== fleet smoke done in {time.time() - t_start:.0f}s ==")
    if _FAILED:
        print(f"FAIL: {len(_FAILED)} checks failed: {_FAILED}")
        return 1
    print("PASS: every fleet check held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
