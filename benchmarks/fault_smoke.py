"""Fault-tolerance smoke check (the ISSUE 6 CI leg, wired in
ci.yml/ci_local.sh).

End-to-end proof that the elastic runtime's recovery paths fire on REAL
fault mechanisms, with the recoveries visible on the live observability
surfaces:

1. **In-process recovery leg** — a supervised ElasticTrainer fit through a
   2-worker multiprocess ETL pipeline with TWO injected faults: a SIGKILLed
   ETL worker (its chunk restarts on a fresh process, output bit-identical)
   and a NaN-poisoned batch (the health monitor flags it, the supervisor
   restores the last good checkpoint and completes). The run must COMPLETE,
   and the live ``/healthz`` must carry the elastic membership section with
   the rollback recorded while ``/metrics`` shows the recovery counters
   (``dl4j_elastic_rollbacks_total``, ``dl4j_etl_worker_restarts_total``).
2. **2-process elastic leg** — two OS processes train under shared-directory
   membership; one SIGKILLs itself mid-epoch. The survivor must miss its
   heartbeats, regroup to world 1, re-shard the batches, and finish all
   epochs.

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/fault_smoke.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def http_get(url: str):
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:  # 503 still carries a body
        return e.code, e.read().decode()


def in_process_recovery_leg(work_dir: str):
    print("== leg 1: ETL-worker kill + NaN rollback under one supervised fit")
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.datavec.executor import (
        MultiProcessTransformExecutor)
    from deeplearning4j_tpu.datavec.transform import Schema, TransformProcess
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel import ElasticTrainer
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.util.faults import (INJECT_NAN, KILL_ETL_WORKER,
                                                get_injector)
    from deeplearning4j_tpu.util.ui_server import UIServer

    # --- injected fault #1: a SIGKILLed ETL worker mid-transform ---------
    schema = Schema.builder().add_column_double("x").build()
    tp = (TransformProcess.builder(schema)
          .double_column_transform("x", _slow_double).build())
    records = [[float(i)] for i in range(512)]
    serial = tp.execute(records)
    get_injector().inject(KILL_ETL_WORKER)
    ex = MultiProcessTransformExecutor(tp, num_workers=2,
                                       min_records_per_worker=64, timeout=60)
    transformed = ex.execute(records)
    snap = tm.get_telemetry().snapshot()
    check("ETL output bit-identical after worker SIGKILL",
          transformed == serial)
    check("worker-restart recovery fired",
          snap["counters"].get("etl.worker_restarts_total", 0) >= 1,
          f"etl.worker_restarts_total="
          f"{snap['counters'].get('etl.worker_restarts_total', 0)}")

    # --- injected fault #2: a NaN batch under the supervised loop --------
    feats = np.asarray([r for r in transformed], np.float32)
    rng = np.random.default_rng(0)
    x = np.concatenate([feats, rng.normal(size=(512, 3))], axis=1).astype(
        np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 512)]
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    get_injector().inject(INJECT_NAN, at_step=5)
    trainer = ElasticTrainer(net, os.path.join(work_dir, "ckpt"),
                             checkpoint_every=3, log_fn=None)
    trainer.fit(ArrayDataSetIterator(x, y, batch=64), epochs=2)
    check("supervised fit completed through the NaN",
          trainer.state == "completed", f"state={trainer.state}")
    check("rollback recovery fired", trainer.rollbacks == 1,
          f"rollbacks={trainer.rollbacks}")
    check("post-rollback params finite",
          all(bool(np.isfinite(np.asarray(l)).all())
              for lyr in net.params for l in lyr.values()))

    # --- the recoveries must be visible on the live server ---------------
    from deeplearning4j_tpu.util.stats import InMemoryStatsStorage

    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())  # attach starts the HTTP server
    try:
        code, body = http_get(f"http://127.0.0.1:{server.port}/healthz")
        check("/healthz answers 200", code == 200, f"HTTP {code}")
        payload = json.loads(body)
        section = payload.get("elastic") or {}
        st = list(section.values())[-1] if section else {}
        check("/healthz has the elastic membership section",
              bool(st), str(sorted(section)))
        check("/healthz reports the completed supervised run",
              st.get("state") == "completed"
              and st.get("membership", {}).get("world") == 1)
        check("/healthz reports the rollback", st.get("rollbacks") == 1)
        code, text = http_get(f"http://127.0.0.1:{server.port}/metrics")
        check("/metrics shows recovery counters",
              "dl4j_elastic_rollbacks_total" in text
              and "dl4j_etl_worker_restarts_total" in text
              and "dl4j_elastic_checkpoints_total" in text)
        check("/metrics shows elastic scrape-time gauges",
              "dl4j_elastic_world_size" in text)
    finally:
        server.stop()
    get_injector().clear()


def _slow_double(v):
    import time

    time.sleep(0.005)  # keep workers alive long enough to be killed
    return v * 2.0


def two_process_elastic_leg(work_dir: str):
    print("== leg 2: 2-process elastic run, one host SIGKILLed mid-epoch")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "_dist_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    d = os.path.join(work_dir, "pod")
    procs = [subprocess.Popen(
        [sys.executable, worker, "--elastic", d, str(pid), "2"]
        + (["2"] if pid == 1 else []),  # pid 1 SIGKILLs itself at step 2
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in (0, 1)]
    out0, err0 = procs[0].communicate(timeout=300)
    procs[1].communicate(timeout=300)
    check("victim died by SIGKILL (no graceful exit)",
          procs[1].returncode == -signal.SIGKILL,
          f"rc={procs[1].returncode}")
    check("survivor exited 0", procs[0].returncode == 0, err0[-400:])
    lines = [l for l in out0.splitlines() if l.startswith("{")]
    r = json.loads(lines[-1]) if lines else {}
    check("survivor completed all epochs",
          r.get("state") == "completed" and r.get("epoch") == 3, str(r))
    check("survivor regrouped to world 1",
          r.get("world_final") == 1 and r.get("regroups", 0) >= 1)
    check("survivor re-sharded the data pipeline",
          r.get("iteration") == 4 + 8 + 8,
          f"iteration={r.get('iteration')} (4 sharded + 8 + 8 re-sharded)")


def main():
    with tempfile.TemporaryDirectory(prefix="dl4j-fault-smoke-") as work:
        in_process_recovery_leg(work)
        two_process_elastic_leg(work)
    if _FAILED:
        print(f"FAIL: {len(_FAILED)} check(s): {_FAILED}")
        return 1
    print("fault smoke OK: every injected fault recovered and was visible "
          "on /healthz + /metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
