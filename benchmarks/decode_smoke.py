"""Decode-path smoke check (the ISSUE 15 CI leg, wired in ci.yml/ci_local.sh).

End-to-end proof of the paged-KV + speculative + int8 acceptance criteria
on a real HTTP server:

1. boot a :class:`ModelServer` with FOUR decoders sharing one set of
   trained weights — ``bert-spec`` (paged + speculative, draft loaded
   from its own archive via ``router.load(draft_path=…)``), ``bert-fp32``
   (paged, no speculation), ``bert-int8`` (weight-only int8 loaded from
   an int8 ModelSerializer archive), ``bert-tiny-pool`` (a deliberately
   undersized block pool) — warm every bucket executable;
2. fire MIXED-LENGTH paged+speculative traffic (prompt lengths crossing
   page boundaries) through real HTTP and assert every speculative
   response is TOKEN-IDENTICAL to the local non-speculative greedy
   reference — and that the steady-state ``serving.recompiles_total``
   delta is exactly 0 (ONE decode executable serves every context
   length, CompileWatcher-asserted);
3. pool exhaustion is a first-class shed: an over-pool request answers
   HTTP 429 + Retry-After, the flight-recorder dump carries the
   ``pool_exhausted`` cause, and the freed pool serves the next request;
4. int8 serving alongside fp32: the int8 model answers the same prompts
   (tokens may legitimately differ — the contract is the pinned logit
   tolerance, pinned in tests/test_paged_decode.py), its resident-bytes
   gauge shows ≥3.5× below the fp32 equivalent on /metrics, and the
   fp32 model's responses stay bit-identical to the local reference;
5. speculation observability: ``serving.spec_accept_rate`` on /metrics,
   ``draft_accept_rate`` on the flight-recorder records, and
   ``concurrent_streams`` beating the contiguous-cache ceiling on the
   pool stats (/v1/models);
6. (ISSUE 16) prefix-heavy traffic at ``bert-prefix`` (radix prefix
   cache + chunked prefill, PINNED pool): concurrent streams sharing one
   system prompt answer TOKEN-IDENTICAL to the oracle cold AND warm,
   ``serving_prefix_cache_hit_rate`` > 0 on /metrics, the steady-state
   recompile delta stays 0 under mixed hit/miss traffic, and the 429
   shed contract survives prefix sharing (flood > pool even after
   eviction);
7. (ISSUE 16) long-prompt burst: chunked prefills in the batch lane
   interleave with interactive decodes — every interactive request
   completes with bounded latency while the burst is in flight, and the
   burst's flight records carry the ``prefill_chunks`` attribution.

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/decode_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []

VOCAB = 48
MAXLEN = 32


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def http_get(url: str):
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def http_post(url: str, obj: dict):
    data = json.dumps(obj).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        r = urllib.request.urlopen(req, timeout=120)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {}
        return e.code, body, dict(e.headers)


def build(tmp):
    import numpy as np  # noqa: F401

    from deeplearning4j_tpu.serving import (Generator, ModelRouter,
                                            ModelServer, ServingModel)
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer
    from deeplearning4j_tpu.zoo.bert import Bert

    net = Bert.tiny(causal=True, task="mlm", vocab_size=VOCAB,
                    max_length=MAXLEN, hidden_dropout=0.0).init()
    draft = Bert.draft(vocab_size=VOCAB, max_length=MAXLEN).init()
    fp32_zip = os.path.join(tmp, "bert.zip")
    int8_zip = os.path.join(tmp, "bert-int8.zip")
    draft_zip = os.path.join(tmp, "draft.zip")
    ModelSerializer.write_model(net, fp32_zip, save_updater=False)
    ModelSerializer.write_model(net, int8_zip, quantize="int8")
    ModelSerializer.write_model(draft, draft_zip, save_updater=False)

    buckets = "batch=1,2,4;seq=8,16"
    router = ModelRouter(name="decode-smoke")
    # speculative target: the draft rides in from its own archive —
    # "loaded per-model via the router" (ISSUE 15 tentpole)
    router.load("bert-spec", fp32_zip, kind="generate", bucketing=buckets,
                block_size=4, draft_path=draft_zip, spec_tokens=3)
    router.load("bert-fp32", fp32_zip, kind="generate", bucketing=buckets,
                block_size=4)
    router.load("bert-int8", int8_zip, kind="generate", bucketing=buckets,
                block_size=4, quantize="int8")
    # 24 blocks of 4 = 96 slots: contiguous ceiling 96//32 = 3 streams,
    # but a 4-stream short-prompt batch fits paged (the beats-the-ceiling
    # check); a long-prompt flood exhausts it (the 429 check)
    router.register(ServingModel(net, "bert-tiny-pool", kind="generate",
                                 bucketing=buckets, block_size=4,
                                 pool_blocks=24),
                    max_wait_ms=1.0, queue_limit=64)
    # shared-prefix + chunked-prefill decoder (ISSUE 16): PINNED pool so
    # the 429 contract stays testable under prefix sharing
    router.register(ServingModel(net, "bert-prefix", kind="generate",
                                 bucketing=buckets, block_size=4,
                                 pool_blocks=24, prefix_cache=True,
                                 prefill_chunk=8),
                    max_wait_ms=1.0, queue_limit=64)
    server = ModelServer(router, port=0).start()  # warms every bucket

    # local greedy reference on the same weights: the token-identity oracle
    ref_gen = Generator(net, paged=False, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8, 16))
    return server, router, ref_gen, fp32_zip, int8_zip


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DL4J_TPU_TRACE_SAMPLE", "1")
    import numpy as np

    from deeplearning4j_tpu.util import telemetry as tm

    tmp = tempfile.mkdtemp(prefix="decode-smoke-")
    print("== decode smoke: paged KV + speculative + int8 over HTTP ==")
    t0 = time.time()
    server, router, ref_gen, fp32_zip, int8_zip = build(tmp)
    print(f"  server up on {server.url} ({time.time() - t0:.1f}s incl. warm)")

    rng = np.random.default_rng(0)
    # mixed context lengths crossing page boundaries (block_size=4)
    prompts = [list(map(int, rng.integers(1, VOCAB, size=n)))
               for n in (2, 3, 5, 7, 9, 13, 17, 21)]
    ref = ref_gen.generate(prompts, max_new_tokens=6)

    def _rec():
        tele = tm.get_telemetry()
        return sum(v for (n, _l), v in tele.counters.items()
                   if n == "serving.recompiles_total")

    rec_before = _rec()

    # -- 2: concurrent mixed-length speculative traffic, token identity
    results = [None] * len(prompts)

    def fire(i):
        results[i] = http_post(
            f"{server.url}/v1/models/bert-spec/generate",
            {"prompt_tokens": [prompts[i]], "max_new_tokens": 6,
             "lane": "batch"})

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    ok_all = all(r is not None and r[0] == 200 for r in results)
    check("all speculative requests answered 200", ok_all)
    if ok_all:
        got = [r[1]["tokens"][0] for r in results]
        check("speculative HTTP decode TOKEN-IDENTICAL to greedy reference",
              got == ref, f"{sum(a == b for a, b in zip(got, ref))}/"
              f"{len(ref)} rows match")
    code, body, _ = http_post(
        f"{server.url}/v1/models/bert-fp32/generate",
        {"prompt_tokens": prompts, "max_new_tokens": 6})
    check("fp32 paged decode bit-identical to reference",
          code == 200 and body.get("tokens") == ref)
    check("steady-state decode recompiles == 0", _rec() - rec_before == 0,
          f"delta {_rec() - rec_before}")

    # -- 3: pool exhaustion = first-class 429 shed; blocks free + reuse
    long_prompt = list(map(int, rng.integers(1, VOCAB, size=20)))
    code, body, headers = http_post(
        f"{server.url}/v1/models/bert-tiny-pool/generate",
        {"prompt_tokens": [long_prompt] * 8, "max_new_tokens": 8})
    check("pool exhaustion answers 429", code == 429, f"code {code}")
    check("pool-exhausted shed carries Retry-After",
          headers.get("Retry-After") is not None)
    check("shed error names PoolExhaustedError",
          body.get("error") == "PoolExhaustedError", str(body)[:100])
    code, dump = http_get(
        f"{server.url}/v1/models/bert-tiny-pool/debug/requests")
    causes = [r.get("cause") for r in json.loads(dump).get("requests", [])]
    check("flight recorder carries the pool_exhausted cause",
          "pool_exhausted" in causes, str(causes[-4:]))
    code, body, _ = http_post(
        f"{server.url}/v1/models/bert-tiny-pool/generate",
        {"prompt_tokens": [prompts[0], prompts[1], prompts[2],
                           prompts[0]], "max_new_tokens": 4})
    check("freed pool serves the next batch (block reuse after shed)",
          code == 200 and len(body.get("tokens", [])) == 4)
    model, _s = router.get("bert-tiny-pool")
    pool = model.generator.pool
    check("paged streams beat the contiguous-cache ceiling",
          pool.peak_streams > pool.contiguous_stream_ceiling(),
          f"peak {pool.peak_streams} > ceiling "
          f"{pool.contiguous_stream_ceiling()}")

    # -- 4: int8 alongside fp32
    code, body, _ = http_post(
        f"{server.url}/v1/models/bert-int8/generate",
        {"prompt_tokens": prompts[:4], "max_new_tokens": 6})
    check("int8 model serves the same traffic", code == 200
          and len(body.get("tokens", [])) == 4)
    m8, _s8 = router.get("bert-int8")
    qp = m8.generator._qp
    check("int8 resident bytes >= 3.5x below fp32",
          qp is not None and qp.fp32_bytes() / qp.resident_bytes() >= 3.5,
          f"ratio {qp.fp32_bytes() / qp.resident_bytes():.2f}" if qp
          else "no qp")
    check("int8 archive >= 3.5x below fp32 archive",
          os.path.getsize(fp32_zip) / os.path.getsize(int8_zip) >= 3.5,
          f"ratio "
          f"{os.path.getsize(fp32_zip) / os.path.getsize(int8_zip):.2f}")
    code, metrics = http_get(f"{server.url}/metrics")
    check("/metrics carries the resident-weight-bytes gauge",
          "serving_weight_bytes" in metrics)

    # -- 5: speculation observability
    check("/metrics carries serving_spec_accept_rate",
          "serving_spec_accept_rate" in metrics)
    check("/metrics carries the KV-pool gauges",
          "serving_kv_pool_blocks_free" in metrics
          and "serving_concurrent_streams" in metrics)
    code, dump = http_get(
        f"{server.url}/v1/models/bert-spec/debug/requests")
    recs = json.loads(dump).get("requests", [])
    ok_recs = [r for r in recs if r.get("status") == "ok"]
    check("flight records carry draft_accept_rate",
          any("draft_accept_rate" in r for r in ok_recs),
          f"{len(ok_recs)} ok records")
    status = router.status()
    spec = status["models"]["bert-spec"].get("speculative")
    check("/v1/models describes the speculative config",
          spec is not None and spec.get("spec_tokens") == 3)
    check("/v1/models describes the KV pool",
          "kv_pool" in status["models"]["bert-fp32"])

    # -- 6: prefix-heavy traffic (shared system prompt), ISSUE 16
    system = list(map(int, rng.integers(1, VOCAB, size=9)))
    shared = [system + list(map(int, rng.integers(1, VOCAB, size=n)))
              for n in (2, 3, 5, 7, 4, 6)]
    pref_ref = ref_gen.generate(shared, max_new_tokens=6)
    rec_before = _rec()

    def fire_prefix(i, out):
        out[i] = http_post(
            f"{server.url}/v1/models/bert-prefix/generate",
            {"prompt_tokens": [shared[i]], "max_new_tokens": 6,
             "lane": "batch"})

    for wave in ("cold", "warm"):
        results = [None] * len(shared)
        threads = [threading.Thread(target=fire_prefix, args=(i, results))
                   for i in range(len(shared))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        ok_all = all(r is not None and r[0] == 200 for r in results)
        check(f"{wave} prefix wave answered 200", ok_all)
        if ok_all:
            got = [r[1]["tokens"][0] for r in results]
            check(f"{wave} prefix-shared decode TOKEN-IDENTICAL to oracle",
                  got == pref_ref,
                  f"{sum(a == b for a, b in zip(got, pref_ref))}/"
                  f"{len(pref_ref)} rows match")
    check("prefix-heavy steady-state recompiles == 0",
          _rec() - rec_before == 0, f"delta {_rec() - rec_before}")
    code, metrics = http_get(f"{server.url}/metrics")
    hit_vals = [float(line.rsplit(" ", 1)[1]) for line in metrics.splitlines()
                if "serving_prefix_cache_hit_rate{" in line]
    check("/metrics carries serving_prefix_cache_hit_rate > 0",
          any(v > 0 for v in hit_vals), f"values {hit_vals}")
    check("/metrics carries serving_chunked_prefill_chunks_total",
          "serving_chunked_prefill_chunks_total" in metrics)
    # the 429 contract survives prefix sharing: even one scheduler batch
    # (4 streams x 7 blocks) needs 28 > the pinned 24, eviction included
    flood = [list(map(int, rng.integers(1, VOCAB, size=20)))
             for _ in range(8)]
    code, body, headers = http_post(
        f"{server.url}/v1/models/bert-prefix/generate",
        {"prompt_tokens": flood, "max_new_tokens": 8})
    check("prefix pool exhaustion still answers 429 + Retry-After",
          code == 429 and headers.get("Retry-After") is not None,
          f"code {code}")
    code, body, _ = http_post(
        f"{server.url}/v1/models/bert-prefix/generate",
        {"prompt_tokens": shared[:2], "max_new_tokens": 4})
    check("prefix pool serves the next batch after the shed",
          code == 200 and body.get("tokens") ==
          [r[:4] for r in pref_ref[:2]])
    pmodel, _ps = router.get("bert-prefix")
    okc, detail = pmodel.generator.pool.conservation()
    check("prefix pool block-refcount conservation", okc, detail)

    # -- 7: long-prompt burst: chunked prefill + interactive interleave
    longs = [system + list(map(int, rng.integers(1, VOCAB, size=7)))
             for _ in range(6)]  # 16 tokens = 2 chunks of 8
    lat = [None] * 6

    def fire_long(i, out):
        out[i] = http_post(
            f"{server.url}/v1/models/bert-prefix/generate",
            {"prompt_tokens": [longs[i]], "max_new_tokens": 6,
             "lane": "batch"})

    def fire_short(i):
        t1 = time.time()
        code, _b, _h = http_post(
            f"{server.url}/v1/models/bert-prefix/generate",
            {"prompt_tokens": [shared[i % len(shared)]],
             "max_new_tokens": 4})
        lat[i] = (code, time.time() - t1)

    results = [None] * len(longs)
    burst = [threading.Thread(target=fire_long, args=(i, results))
             for i in range(len(longs))]
    inter = [threading.Thread(target=fire_short, args=(i,))
             for i in range(6)]
    for t in burst:
        t.start()
    for t in inter:
        t.start()
    for t in burst + inter:
        t.join(timeout=120)
    check("long-prompt burst answered 200",
          all(r is not None and r[0] == 200 for r in results))
    ok_inter = all(x is not None and x[0] == 200 for x in lat)
    check("interactive decodes complete during the burst", ok_inter)
    if ok_inter:
        worst = max(d for _, d in lat)
        check("interactive p99 bounded under chunked-prefill burst",
              worst < 15.0, f"worst {worst:.2f}s")
    code, dump = http_get(
        f"{server.url}/v1/models/bert-prefix/debug/requests")
    recs = json.loads(dump).get("requests", [])
    check("flight records carry prefill_chunks attribution",
          any(r.get("prefill_chunks", 0) >= 2 for r in recs),
          f"{len(recs)} records")
    check("flight records carry prefix_hit_rate attribution",
          any("prefix_hit_rate" in r for r in recs))

    server.stop()
    print(f"== {'PASS' if not _FAILED else 'FAIL'} "
          f"({time.time() - t0:.1f}s, {len(_FAILED)} failed) ==")
    if _FAILED:
        print("failed checks:", ", ".join(_FAILED))
    return 1 if _FAILED else 0


if __name__ == "__main__":
    sys.exit(main())
