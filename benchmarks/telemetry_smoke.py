"""Telemetry smoke check (the ISSUE 4 CI leg, wired in ci.yml/ci_local.sh).

End-to-end proof of the unified-telemetry acceptance criteria on a tiny
2-step-per-epoch pipeline that still exercises every instrumented layer:

1. multiprocess ETL (forked workers, spans shipped over the result pipe)
   → device prefetch thread → bucketed MultiLayerNetwork fit with a
   TrainingHealthMonitor + coalesced listener dispatch;
2. the UI server's ``/metrics`` (Prometheus text: compile, step-time,
   queue-depth, and HBM/device gauges) and ``/healthz`` (JSON, HTTP 200)
   — fetched with the real ``curl`` binary when present (``--no-curl``
   or a curl-less image falls back to urllib; either way it is a real
   HTTP round-trip through the live server);
3. the merged Chrome/Perfetto trace: loads as JSON, every event passes the
   schema check (name/ph/pid/tid/ts, durations on 'X' events), and spans
   from ≥ 3 distinct PID/thread rows are present (main loop + prefetch
   thread + ETL worker processes).

Exit 0 on success, 1 with a FAIL line on any violated check.

    JAX_PLATFORMS=cpu python benchmarks/telemetry_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAILED = []


def check(name: str, ok: bool, detail: str = ""):
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        _FAILED.append(name)


def http_get(url: str, use_curl: bool):
    """(status, body) via curl when available (the CI leg's literal
    requirement), urllib otherwise."""
    if use_curl and shutil.which("curl"):
        out = subprocess.run(
            ["curl", "-sS", "-w", "\n%{http_code}", url],
            capture_output=True, text=True, timeout=30)
        body, _, code = out.stdout.rpartition("\n")
        if not code.strip().isdigit():
            # connection refused etc.: surface as a failed check, not a
            # ValueError traceback that masks the real server problem
            return 0, f"curl failed: {out.stderr.strip()}"
        return int(code), body
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def run_pipeline():
    """mp-ETL → prefetch → bucketed 2-step fit, all instrumented."""
    import numpy as np

    from deeplearning4j_tpu.data import AsyncDataSetIterator
    from deeplearning4j_tpu.datavec import (
        CollectionRecordReader, ParallelTransformRecordReader,
        RecordReaderDataSetIterator, Schema, TransformProcess)
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import InMemoryStatsStorage, StatsListener
    from deeplearning4j_tpu.util.health import TrainingHealthMonitor

    rng = np.random.default_rng(0)
    n, n_features = 256, 4
    records = [[float(v) for v in rng.normal(size=n_features)]
               + [int(rng.integers(0, 3))] for _ in range(n)]
    schema_b = Schema.builder()
    schema_b.add_column_double(*[f"f{i}" for i in range(n_features)])
    schema_b.add_column_integer("label")
    tp = (TransformProcess.builder(schema_b.build())
          .double_math_op("f0", "multiply", 2.0).build())
    reader = ParallelTransformRecordReader(
        CollectionRecordReader(records), tp, num_workers=2)
    # force the multiprocess path on tiny input (below the serial cutoff
    # the executor would keep ALL 256 records in-process)
    reader.executor.min_records_per_worker = 8
    it = RecordReaderDataSetIterator(reader, batch_size=128,
                                     label_index=n_features, num_classes=3)
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .sync_every(2).batch_buckets((128,)).list()
            .layer(DenseLayer(n_in=n_features, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(n_features)).build())
    net = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(TrainingHealthMonitor(window=2, log_fn=None),
                      StatsListener(storage, collect_histograms=False))
    net.fit(AsyncDataSetIterator(it, buffer_size=2), epochs=1)  # 2 steps
    return net, storage


def validate_trace(trace: dict):
    events = trace.get("traceEvents")
    check("trace has traceEvents list", isinstance(events, list)
          and len(events) > 0, f"{len(events or [])} events")
    bad = [e for e in events
           if not (isinstance(e.get("name"), str)
                   and e.get("ph") in ("X", "i", "M")
                   and isinstance(e.get("pid"), int)
                   and isinstance(e.get("tid"), int)
                   and (e["ph"] == "M" or isinstance(
                       e.get("ts"), (int, float)))
                   and (e["ph"] != "X" or isinstance(
                       e.get("dur"), (int, float))))]
    check("every event passes the schema", not bad,
          f"{len(bad)} malformed" if bad else "")
    rows = {(e["pid"], e["tid"]) for e in events if e["ph"] == "X"}
    pids = {p for p, _ in rows}
    check("spans from >= 3 distinct PID/thread rows", len(rows) >= 3,
          f"{len(rows)} rows across {len(pids)} processes")
    names = {e["name"] for e in events}
    for expected in ("mln.train_step", "prefetch.etl_wait",
                     "etl.transform_chunk", "listeners.flush"):
        check(f"span {expected!r} present", expected in names)
    if hasattr(os, "fork"):
        check("ETL worker PIDs differ from the main process",
              len(pids) >= 2, f"pids={sorted(pids)}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0,
                    help="UI server port (0 = ephemeral)")
    ap.add_argument("--trace", default="/tmp/dl4j_telemetry_trace.json")
    ap.add_argument("--no-curl", action="store_true",
                    help="fetch endpoints with urllib instead of curl")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.util import telemetry as tm
    from deeplearning4j_tpu.util.ui_server import UIServer

    tm.set_enabled(True)

    print("== 2-step fit through mp-ETL + prefetch + bucketed dispatch ==")
    net, storage = run_pipeline()
    check("fit ran 2 iterations", net.iteration == 2,
          f"iteration={net.iteration}")
    check("stats records carry the telemetry group",
          bool(storage.records) and "telemetry" in storage.records[-1])

    print("== /metrics + /healthz on the live UI server ==")
    ui = UIServer(port=args.port)
    ui.attach(storage)
    base = f"http://127.0.0.1:{ui.port}"
    use_curl = not args.no_curl
    try:
        status, metrics = http_get(base + "/metrics", use_curl)
        check("/metrics serves 200", status == 200, f"status={status}")
        for metric in ("dl4j_xla_backend_compiles_total",
                       "dl4j_train_step_seconds_count",
                       "dl4j_prefetch_queue_depth",
                       "dl4j_train_steps_total",
                       "dl4j_etl_chunks_total",
                       "dl4j_health_loss_ewma"):
            check(f"/metrics exposes {metric}", metric in metrics)
        check("/metrics exposes device gauges",
              "dl4j_device_bytes_in_use" in metrics
              or 'platform="cpu"' in metrics
              or "dl4j_compile_cache_enabled" in metrics,
              "CPU backend may omit memory_stats; collector line required")
        status, health = http_get(base + "/healthz", use_curl)
        check("/healthz serves 200", status == 200, f"status={status}")
        doc = json.loads(health)
        check("/healthz reports ok", doc.get("status") == "ok",
              json.dumps(doc)[:120])
        check("/healthz includes the monitor's checks",
              "training.finite" in doc.get("checks", {}))
    finally:
        ui.stop()

    print("== merged Chrome/Perfetto trace ==")
    tele = tm.get_telemetry()
    path = tele.write_chrome_trace(args.trace)
    with open(path) as f:
        trace = json.load(f)
    validate_trace(trace)

    if _FAILED:
        print(f"FAIL: {len(_FAILED)} check(s): {', '.join(_FAILED)}")
        return 1
    print(f"telemetry smoke: all checks passed (trace at {path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
