"""Compile-once A/B sweep: shape bucketing x persistent compilation cache.

PR 1 tuned the device step (fusion_sweep.py), PR 2 the host pipeline
(host_pipeline_sweep.py); this harness measures the remaining systematic
waste — XLA RECOMPILATION — and the two levers ISSUE 3 builds against it:

  bucketing   ragged batches pad to a fixed bucket set (data/bucketing.py):
              a ragged-tail epoch (N % B != 0) must trace the train step
              exactly ONCE (0 extra compiles) vs >= 1 extra without
  cache       the persistent on-disk compilation cache
              (util/compile_cache.py): a second PROCESS against the same
              cache dir deserializes executables instead of recompiling —
              cold-start wall drops and backend-compile counts collapse

Every cell runs in a fresh child process (compile state is process-global;
only a cold process measures cold start honestly). The child trains a
ragged-tail epoch on the LeNet-5 bench model (flagship-independent, no
BatchNorm — bucketing's bit-identity regime) and reports trace counts from
the CompileWatcher, process-global backend compiles, persistent-cache hits,
and launch-to-first-step wall. Wall cells are median-of-3 with the standard
``noise`` field (BASELINE.md methodology).

Usage::

    python benchmarks/compile_cache_sweep.py             # full table
    python benchmarks/compile_cache_sweep.py --runs 1    # quick look
    python benchmarks/compile_cache_sweep.py --json out.json
    python benchmarks/compile_cache_sweep.py --ci        # assert-mode:
        # one shared cache dir, two processes: the second's backend-compile
        # count must DROP and its cache hits must be > 0; bucketed ragged
        # epoch must add 0 extra traces while unbucketed adds >= 1.
        # Exits nonzero on violation (the CI cache leg runs this).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

# runnable as `python benchmarks/compile_cache_sweep.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _med3  # noqa: E402

_CHILD = r"""
import json, sys, time
T0 = time.perf_counter()
import jax
jax.config.update("jax_platforms", "cpu")
cfg = json.loads(sys.argv[1])
if cfg["cache_dir"]:
    from deeplearning4j_tpu.util.compile_cache import enable_persistent_cache
    enable_persistent_cache(cfg["cache_dir"])
import numpy as np
from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.util import get_watcher

w = get_watcher()   # install monitoring hooks BEFORE any compile happens
b = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
     # explicit on BOTH axes: cfg decides, never an ambient env default
     .batch_buckets(tuple(cfg["buckets"]) if cfg["buckets"] else None)
     .seq_buckets(None))
conf = (b.list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5), padding="VALID",
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_in=32, n_out=10))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
B, N = cfg["batch"], cfg["n"]
x = rng.normal(size=(N, 28, 28, 1)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, N)]
t_first = None
it = ArrayDataSetIterator(x, y, batch=B)
for epoch in range(2):
    it.reset()
    for ds in it:
        net._fit_batch(ds.features, ds.labels)
        if t_first is None:
            float(net.score_value)
            t_first = time.perf_counter() - T0
float(net.score_value)
counts = w.counts()
print(json.dumps({
    "cold_start_s": round(t_first, 3),
    "total_s": round(time.perf_counter() - T0, 3),
    "step_traces": w.traces.get("MultiLayerNetwork.train_step", 0),
    "backend_compiles": counts["backend_compiles"],
    # jax logs a backend_compile event even on a persistent-cache hit; the
    # honest recompile count subtracts the hits
    "uncached_compiles": counts["uncached_compiles"],
    "compile_seconds": round(w.backend_compile_seconds, 3),
    "persistent_cache_hits": w.persistent_cache_hits,
}))
"""


def run_child(buckets, cache_dir, batch=8, n=20):
    cfg = {"buckets": buckets, "cache_dir": cache_dir, "batch": batch, "n": n}
    # scrub inherited DL4J_TPU_* knobs: an ambient DL4J_TPU_BUCKETS would
    # bucket the "unbucketed" baseline, an ambient DL4J_TPU_COMPILE_CACHE
    # would un-uncache the nocache cells — only cfg controls the A/B
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DL4J_TPU_")}
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", _CHILD, json.dumps(cfg)], env=env,
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    if not lines:
        raise RuntimeError(
            f"child failed (rc={out.returncode}):\n{out.stderr[-2000:]}")
    return json.loads(lines[-1])


def sweep(runs: int, batch: int, n: int):
    """Full table: {no cache, cache cold, cache warm} x {bucketing off/on}."""
    rows = []
    for buckets in (None, [batch]):
        label = f"bucketing={'on' if buckets else 'off'}"
        td = tempfile.mkdtemp(prefix="dl4j_cc_sweep_")
        try:
            samples = {"nocache": [], "cold": [], "warm": []}

            def one():
                shutil.rmtree(td, ignore_errors=True)
                os.makedirs(td, exist_ok=True)
                samples["nocache"].append(run_child(buckets, None, batch, n))
                samples["cold"].append(run_child(buckets, td, batch, n))
                samples["warm"].append(run_child(buckets, td, batch, n))
                return samples["warm"][-1]["cold_start_s"] / \
                    samples["cold"][-1]["cold_start_s"]

            ratio, noise = _med3(one, runs=runs) if runs > 1 else (one(), "n/a")
            med = lambda key, field: sorted(  # noqa: E731
                s[field] for s in samples[key])[len(samples[key]) // 2]
            rows.append({
                "config": label,
                "step_traces_ragged_epoch": med("nocache", "step_traces"),
                "nocache_cold_start_s": med("nocache", "cold_start_s"),
                "cache_cold_start_s": med("cold", "cold_start_s"),
                "cache_warm_start_s": med("warm", "cold_start_s"),
                "warm_over_cold": round(ratio, 4),
                "warm_over_cold_noise": noise,
                "cold_uncached_compiles": med("cold", "uncached_compiles"),
                "warm_uncached_compiles": med("warm", "uncached_compiles"),
                "warm_cache_hits": med("warm", "persistent_cache_hits"),
            })
        finally:
            shutil.rmtree(td, ignore_errors=True)
    return rows


def print_table(rows):
    cols = ["config", "step_traces_ragged_epoch", "nocache_cold_start_s",
            "cache_cold_start_s", "cache_warm_start_s", "warm_over_cold",
            "cold_uncached_compiles", "warm_uncached_compiles",
            "warm_cache_hits"]
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows)) for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r[c]).ljust(widths[c]) for c in cols))


def ci_check(batch: int, n: int) -> int:
    """Assert-mode for the CI cache leg: exits nonzero on any violation."""
    failures = []
    td = tempfile.mkdtemp(prefix="dl4j_cc_ci_")
    try:
        cold = run_child([batch], td, batch, n)
        warm = run_child([batch], td, batch, n)
    finally:
        shutil.rmtree(td, ignore_errors=True)
    print(f"cold: {json.dumps(cold)}")
    print(f"warm: {json.dumps(warm)}")
    if not warm["uncached_compiles"] < cold["uncached_compiles"]:
        failures.append(
            f"warm-process compile count did not drop "
            f"({warm['uncached_compiles']} vs {cold['uncached_compiles']} "
            "uncached compiles)")
    if not warm["persistent_cache_hits"] > 0:
        failures.append("warm process saw 0 persistent-cache hits")
    bucketed = run_child([batch], None, batch, n)
    unbucketed = run_child(None, None, batch, n)
    print(f"bucketed ragged epoch:   traces={bucketed['step_traces']}")
    print(f"unbucketed ragged epoch: traces={unbucketed['step_traces']}")
    if bucketed["step_traces"] != 1:
        failures.append(
            f"bucketed ragged epoch traced {bucketed['step_traces']}x "
            "(want exactly 1 — 0 extra compiles)")
    if unbucketed["step_traces"] < 2:
        failures.append(
            f"unbucketed ragged epoch traced {unbucketed['step_traces']}x "
            "(want >= 2 — the ragged tail must cost a compile)")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print("compile-cache CI check: OK")
    return 1 if failures else 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--runs", type=int, default=3,
                    help="median-of-N for the wall cells (default 3)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--n", type=int, default=20,
                    help="examples per epoch (N %% batch != 0 => ragged)")
    ap.add_argument("--json", help="also write rows as JSON to this path")
    ap.add_argument("--ci", action="store_true",
                    help="assert-mode (cache-hit drop + 0-extra-compile "
                         "bucketing); exits nonzero on violation")
    args = ap.parse_args()
    if args.n % args.batch == 0:
        ap.error("--n must not be divisible by --batch (ragged tail needed)")
    if args.ci:
        sys.exit(ci_check(args.batch, args.n))
    rows = sweep(args.runs, args.batch, args.n)
    print_table(rows)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(rows, fh, indent=2)
        print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
