#!/usr/bin/env bash
# Shell transcription of .github/workflows/ci.yml (VERDICT r5 weak #5: the
# YAML itself has never executed on a GitHub runner). Each step below mirrors
# one `steps:` entry so the job's commands and env are exercised locally;
# what CANNOT be validated here is the Actions plumbing itself (checkout@v4,
# setup-python@v5, the pip resolve against pypi.org and the apt install on
# the ubuntu-latest image) — those steps degrade to presence checks.
#
#   bash .github/ci_local.sh              # full suite, exact CI env
#   bash .github/ci_local.sh -m 'not slow'  # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== step: checkout (actions/checkout@v4) =="
test -d .git && echo "repo present: $(git rev-parse --short HEAD)"

echo "== step: setup-python (actions/setup-python@v5, wants 3.12) =="
python --version

echo "== step: Install (pip install jax ... torch) =="
# No network installs locally; validate the dependency set the step would
# produce by importing every package it names.
python - <<'EOF'
import importlib
for mod in ("jax", "flax", "optax", "orbax.checkpoint", "chex", "einops",
            "numpy", "PIL", "pyarrow", "pytest", "tensorflow", "torch"):
    importlib.import_module(mod)
    print(f"  import {mod}: ok")
EOF

echo "== step: Native build deps (g++, libjpeg, libpng) =="
g++ --version | head -1
# the native runtime self-compiles on first import; jpeg/png headers gate
# the image leg (native/__init__.py degrades without them)
for h in /usr/include/jpeglib.h /usr/include/png.h; do
    if [ -e "$h" ]; then echo "  $h: present"; else echo "  $h: MISSING (image leg will skip)"; fi
done

echo "== step: Host-pipeline tests (2-worker multiprocess ETL leg) =="
# ISSUE 2: the async host-pipeline suite under a FORCED 2-worker executor —
# DL4J_TPU_ETL_WORKERS pins the worker count so the multiprocess merge path
# (not the auto-sized or serial fallback) is what the bit-identity tests hit.
DL4J_TPU_ETL_WORKERS=2 \
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/test_host_pipeline.py -q

echo "== step: Compile-cache tests (persistent cache, two runs warm/cold) =="
# ISSUE 3: the bucketing/compile-once suite twice against ONE persistent
# compilation_cache_dir (second run starts warm), then the sweep's --ci
# assertions: warm-process compile count drops (cache hits > 0), bucketed
# ragged epoch adds 0 extra traces, unbucketed adds >= 1.
CC_DIR=$(mktemp -d /tmp/dl4j-ci-compile-cache.XXXXXX)
JAX_PLATFORMS=cpu DL4J_TPU_COMPILE_CACHE="$CC_DIR" \
    python -m pytest tests/test_compile_cache.py -q
JAX_PLATFORMS=cpu DL4J_TPU_COMPILE_CACHE="$CC_DIR" \
    python -m pytest tests/test_compile_cache.py -q
JAX_PLATFORMS=cpu python benchmarks/compile_cache_sweep.py --ci
rm -rf "$CC_DIR"

echo "== step: Telemetry smoke (2-step fit, /metrics + /healthz, trace schema) =="
# ISSUE 4: full observability chain — a 2-step fit through mp-ETL + prefetch
# + bucketed dispatch with the health monitor on, then the script curls the
# live server's /metrics (Prometheus text incl. compile/step-time/queue-
# depth gauges) and /healthz, and validates the merged Chrome trace loads
# with spans from >= 3 distinct PIDs/threads (event schema check).
JAX_PLATFORMS=cpu python benchmarks/telemetry_smoke.py

echo "== step: Fault-tolerance smoke (ETL kill + NaN rollback + host SIGKILL) =="
# ISSUE 6: every injected fault takes its recovery path on the REAL
# mechanism — SIGKILLed ETL worker's chunk restarts (bit-identical output),
# NaN batch rolls back to the last good checkpoint and completes, and a
# 2-process elastic pod survives one host SIGKILLed mid-epoch (survivor
# regroups + re-shards); recoveries visible on /healthz + /metrics.
JAX_PLATFORMS=cpu python benchmarks/fault_smoke.py

echo "== step: GSPMD sharded-fit bit-identity + ZeRO memory =="
# ISSUE 7: the deterministic lane mode must make an 8-virtual-device
# sharded fit BIT-identical to the single-device fit (params, Adam
# moments, RNG key) on dense MLN / multi-io CG / TBPTT-LSTM topologies,
# ZeRO must cut optimizer-state bytes/device ~8x, elastic reshard must
# recompile onto the shrunken mesh, and the sharded cost report must
# expose honest per-device + global totals.
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/test_gspmd_identity.py -q

echo "== step: Serving smoke (model server + continuous batching + drain) =="
# ISSUE 8: the HTTP model server (dense classifier + causal BERT-tiny
# KV-cache decoder) under concurrent mixed-model traffic — all 200s, p99
# under the sanity bound, steady-state serving.recompiles_total delta 0,
# bit-identical classify responses, 429/404 shed contract, /metrics +
# /healthz serving surfaces, graceful drain -> 503.
JAX_PLATFORMS=cpu python benchmarks/serving_smoke.py

echo "== step: Resilience smoke (reload storm + fault recoveries + brownout) =="
# ISSUE 13: the serving resilience layer end-to-end on real HTTP — 5
# rolling reloads under mixed traffic (zero shed, zero recompiles, version
# advancing), corrupt archive -> 409 with the old version still serving,
# serving_worker_crash -> 500 + flight cause + supervised restart,
# serving_compute_error -> breaker open (503 + Retry-After) then half-open
# probe closes, serving_slow_batch -> deadline shed behind the stall, SLO
# exhaustion -> batch-lane brownout while interactive serves, clean drain.
JAX_PLATFORMS=cpu python benchmarks/resilience_smoke.py

echo "== step: Decode smoke (paged KV + speculative + int8 + prefix cache over HTTP) =="
# ISSUE 15: the planet-scale decode path on real HTTP — mixed-length
# paged+speculative traffic TOKEN-IDENTICAL to the non-speculative greedy
# reference with 0 steady-state recompiles, pool exhaustion -> first-class
# 429 + Retry-After + pool_exhausted flight cause + block reuse after the
# shed, paged concurrent streams beating the contiguous-cache ceiling,
# int8 serving alongside fp32 (resident + archive bytes >= 3.5x below
# fp32, gauge-asserted), spec_accept_rate/draft_accept_rate surfaces.
# Plus the ISSUE 16 legs: prefix-heavy traffic (shared system prompt)
# token-identical cold AND warm with hit_rate > 0, 0 recompiles and the
# 429 contract intact under prefix sharing; long-prompt chunked-prefill
# burst with bounded interactive latency.
JAX_PLATFORMS=cpu python benchmarks/decode_smoke.py

echo "== step: Fleet smoke (2-worker prefix-affinity routing over real processes) =="
# ISSUE 18: the disaggregated serving fleet end-to-end — a FleetRouter over
# 2 real worker processes: mixed classify+generate traffic all-200s and
# token-identical to a single-process oracle loaded from the same archives,
# 0 steady-state recompiles per worker, prefix-affinity routing decisions
# and per-worker prefix_cache_hit_rate >= the single-process value scraped
# from the fleet /metrics fan-in, one worker SIGKILLed mid-burst with zero
# request loss after client retry + respawn back into the ring, and a
# fleet-wide rolling reload under live traffic with zero shed and every
# worker's version advancing.
JAX_PLATFORMS=cpu python benchmarks/fleet_smoke.py

echo "== step: Kernel-engine equivalence (Pallas interpret, fused optimizer) =="
# ISSUE 9: the hot-path kernel suite with the dispatch knob FORCED to
# pallas — off-TPU that is the Pallas interpreter, bit-faithful to the
# kernel block program — under 8 virtual devices for the ZeRO-sharded
# fused-buffer leg: conv fwd/grads grid vs lax conv, LSTM cell/sequence/
# TBPTT trajectories vs the exact scan, fused optimizer bit-identity vs
# per-leaf, dynamic loss-scale skip/grow, masked flash vs exact.
DL4J_TPU_KERNEL_IMPL=pallas \
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/test_kernels.py -q

echo "== step: Compression smoke (conservation + t->0 identity + wire ratio) =="
# ISSUE 10: the encoded gradient all-reduce on 8 virtual devices —
# error-feedback conservation bit-exact, threshold->0 fit bit-identical to
# the uncompressed deterministic lane path, wire-bytes counter > 0 and
# sparse ratio < 0.1 once the adaptive threshold reaches its target band.
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/compression_smoke.py

echo "== step: Autotune smoke (sweep + planted gates + warm DB + dispatch) =="
# ISSUE 11: the autotuning machinery end-to-end — cold sweep with a
# planted-slow candidate (loses) and a planted-wrong candidate (rejected
# by the equivalence gate), deterministic DB across independent cold
# sweeps, warm process re-measures nothing, and kernel_impl=auto dispatch
# resolves through the armed database at trace time.
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/autotune_smoke.py

echo "== step: Pipeline smoke (3D mesh: bytes/device + trajectory + compose) =="
# ISSUE 14: the pipeline-parallel fit() on the (data=2, model=2, pipe=2)
# 8-virtual-device mesh — a model whose replicated param+optimizer
# footprint busts a per-device budget places at ~1/pipe_stages
# bytes/device and trains; the fit tracks the unpipelined trajectory and
# is BIT-identical across data folds with the pipe placement fixed;
# grad_compression t->0 composes bit-identically under ZeRO; the bubble
# fraction equals the GPipe schedule expression (computed, never timed).
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/pipeline_smoke.py

echo "== step: Perf-regression gate (BENCH bands + injected-regression self-test) =="
# ISSUE 5: the committed BENCH_r*.json trajectory becomes machine-checked
# bands (noise-aware, direction-aware); the latest record must pass, and
# the self-test must prove the gate FAILS on a synthetic regression.
python benchmarks/regression_gate.py --ci

echo "== step: Test (pytest, JAX_PLATFORMS=cpu, 8 virtual devices) =="
_pytest_t0=$(date +%s)
JAX_PLATFORMS=cpu \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -q "$@"
_pytest_wall=$(( $(date +%s) - _pytest_t0 ))
echo "pytest wall-clock: ${_pytest_wall}s"

# Tier-1 runtime guard (ISSUE 11 satellite): the driver's tier-1 command
# runs `-m 'not slow'` under a hard 870s timeout — a run that creeps past
# it stops reporting results at all, so the budget must never regress
# SILENTLY. When this script is invoked with the tier-1 marker set, fail
# loudly at 850s: new heavy tests must be `slow`-marked (ROADMAP) or a
# cheap sibling must take their seam over.
case "$*" in
  *"not slow"*)
    if [ "${_pytest_wall}" -gt 850 ]; then
        echo "TIER-1 RUNTIME GUARD: wall-clock ${_pytest_wall}s exceeds" \
             "the 850s guard (hard driver timeout: 870s)." >&2
        echo "slow-mark the offenders (pytest --durations=30) before the" \
             "budget dies silently." >&2
        exit 1
    fi
    echo "tier-1 runtime guard: ${_pytest_wall}s <= 850s budget guard"
    ;;
esac
