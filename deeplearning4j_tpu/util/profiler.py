"""Profiling + correctness guards: op profiler, Chrome trace, NaN panic.

Reference parity (SURVEY.md §5.1–5.2):
- OpProfiler / ProfilerConfig      org/nd4j/linalg/profiler/{OpProfiler,ProfilerConfig}.java
  (per-op wall time + invocation counts, enabled on the executioner via
  profilingConfigurableHookIn/Out)
- ProfilingListener (Chrome trace) org/nd4j/autodiff/listeners/profiler/ProfilingListener.java
- NaN/Inf panic                    OpExecutionerUtil.checkForAny via ProfilerConfig.nanPanic
- PerformanceTracker (bandwidth)   org/nd4j/linalg/memory/PerformanceTracker-style counters

TPU-native notes: under jit there is no per-op host boundary to hook — XLA
fuses the graph — so per-op timing instruments the *eager/by-name* dispatch
path (exec_op), exactly where the reference hooks DefaultOpExecutioner, and
whole-step timing comes from the listeners. For kernel-level depth the JAX
profiler (jax.profiler.trace → TensorBoard/XPlane) is exposed via
``device_trace``; the Chrome-trace exporter writes the same
chrome://tracing JSON the reference's ProfilingListener produces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class ProfilerConfig:
    """ProfilerConfig.java parity."""

    profile_ops: bool = True
    check_for_nan: bool = False      # nanPanic
    check_for_inf: bool = False
    stack_trace: bool = False        # record call sites per op


class OpProfiler:
    """Singleton per-op timing/count profiler (OpProfiler.getInstance parity).

    Wraps the registry's exec_op; use ``start()``/``stop()`` or the
    ``profile()`` context manager. Times are host wall-clock including device
    sync (the honest eager number)."""

    _instance: Optional["OpProfiler"] = None

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.config = config or ProfilerConfig()
        self.reset()
        self._orig_exec = None

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def reset(self):
        self.invocations: Dict[str, int] = defaultdict(int)
        self.total_ns: Dict[str, int] = defaultdict(int)
        # chrome trace events; ts/dur in WALL ns (time.time_ns) so this
        # trace and the telemetry trace share one timebase and load into
        # one Perfetto view (export subtracts telemetry.trace_epoch_ns())
        self.events: List[dict] = []

    # -- hook ---------------------------------------------------------------
    def start(self):
        """Install the exec hook (profilingHookIn/Out parity)."""
        from deeplearning4j_tpu.ops import registry

        if self._orig_exec is not None:
            return self
        orig = registry.exec_op
        cfg = self.config
        prof = self

        def wrapped(name, *args, **kwargs):
            t0 = time.time_ns()
            out = orig(name, *args, **kwargs)
            out = jax.block_until_ready(out)
            t1 = time.time_ns()
            if cfg.profile_ops:
                prof.invocations[name] += 1
                prof.total_ns[name] += t1 - t0
                prof.events.append({
                    "name": name, "ph": "X", "pid": 0, "tid": 0,
                    "ts": t0, "dur": t1 - t0,  # wall ns; export converts
                })
            if cfg.check_for_nan or cfg.check_for_inf:
                _panic_check(name, out, cfg)
            return out

        registry.exec_op = wrapped
        self._orig_exec = orig
        return self

    def stop(self):
        from deeplearning4j_tpu.ops import registry

        if self._orig_exec is not None:
            registry.exec_op = self._orig_exec
            self._orig_exec = None
        return self

    @contextlib.contextmanager
    def profile(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()

    # -- reporting ----------------------------------------------------------
    def summary(self) -> str:
        """printOutDashboard parity: per-op totals sorted by time."""
        rows = sorted(self.total_ns.items(), key=lambda kv: -kv[1])
        lines = [f"{'op':<32}{'calls':>8}{'total ms':>12}{'mean us':>12}"]
        for name, ns in rows:
            n = self.invocations[name]
            lines.append(
                f"{name:<32}{n:>8}{ns / 1e6:>12.3f}{ns / 1e3 / max(n, 1):>12.1f}")
        return "\n".join(lines)

    def write_chrome_trace(self, path: str):
        """ProfilingListener parity: chrome://tracing JSON. Timestamps are
        exported relative to the process-shared trace epoch
        (telemetry.trace_epoch_ns()), so this file and a
        ``Telemetry.write_chrome_trace`` file from the same run load into
        ONE Perfetto view on the same wall-clock timeline."""
        from deeplearning4j_tpu.util.telemetry import trace_epoch_ns

        t0 = trace_epoch_ns()
        if self.events:
            t0 = min(t0, min(e["ts"] for e in self.events))
        out = [dict(e, ts=(e["ts"] - t0) / 1e3, dur=e["dur"] / 1e3)
               for e in self.events]
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)


class NaNPanicError(FloatingPointError):
    pass


def _panic_check(name, out, cfg):
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if cfg.check_for_nan and np.isnan(arr).any():
            raise NaNPanicError(f"NaN produced by op {name!r} (nanPanic)")
        if cfg.check_for_inf and np.isinf(arr).any():
            raise NaNPanicError(f"Inf produced by op {name!r} (infPanic)")


def check_numerics(tree, where: str = ""):
    """OpExecutionerUtil.checkForAny parity, usable on any pytree (params,
    grads) from user code or listeners. The error names the pytree KEY-PATH
    of every offending leaf (``jax.tree_util.tree_flatten_with_path``) with
    its shape and nan/inf counts — not just the enclosing ``where`` label —
    so a single bad layer is identifiable without a debugger."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        finite = np.isfinite(arr)
        if finite.all():
            continue
        key = jax.tree_util.keystr(path)
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        bad.append(f"{where}{key} shape={tuple(arr.shape)} "
                   f"nan={n_nan} inf={n_inf}")
    if bad:
        raise NaNPanicError(
            "non-finite values at " + "; ".join(bad))


@contextlib.contextmanager
def device_trace(logdir: str):
    """Kernel-level device profile via the JAX profiler (TensorBoard/XPlane
    format — the depth the reference never had)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# XPlane parsing (no tensorboard_plugin_profile / TF xplane_pb2 dependency)
# ---------------------------------------------------------------------------
# The .xplane.pb files jax.profiler writes follow tsl/profiler/protobuf/
# xplane.proto. Only the containment chain XSpace.planes(1) -> XPlane
# {name=2, lines=3, event_metadata=4} -> XLine {name=2, events=4} -> XEvent
# {metadata_id=1, duration_ps=3} is needed for device-time totals, so a
# minimal protobuf wire-format reader keeps the roof-proof recipe
# self-contained (the TF builds in this image ship no xplane_pb2).


def _wire_iter(buf: bytes):
    """Yield (field_number, wire_type, value) over one protobuf message.
    value: int for varint(0)/fixed(1,5), bytes for length-delimited(2)."""
    i, n = 0, len(buf)
    while i < n:
        key = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, v
        elif wt == 1:  # 64-bit
            yield field, wt, int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:  # length-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:  # 32-bit
            yield field, wt, int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")


def parse_xplane(path: str) -> List[dict]:
    """Parse one .xplane.pb into ``[{'name': plane, 'lines': [{'name': line,
    'events': [(name, dur_ps, offset_ps)]}]}]``. Event names resolve through
    the plane's event_metadata table; the offset (XEvent.offset_ps, line-
    relative) lets consumers dedupe NESTED events on one thread line —
    the cost-attribution grouper only counts outermost matches."""
    with open(path, "rb") as f:
        space = f.read()
    planes = []
    for field, wt, val in _wire_iter(space):
        if field != 1 or wt != 2:
            continue
        name, lines, meta = "", [], {}
        for pf, pwt, pv in _wire_iter(val):
            if pf == 2 and pwt == 2:
                name = pv.decode("utf-8", "replace")
            elif pf == 3 and pwt == 2:
                lines.append(pv)
            elif pf == 4 and pwt == 2:  # map entry: key=1, value=2(XEventMetadata)
                k, mname = None, ""
                for mf, mwt, mv in _wire_iter(pv):
                    if mf == 1 and mwt == 0:
                        k = mv
                    elif mf == 2 and mwt == 2:
                        for ef, ewt, ev in _wire_iter(mv):
                            if ef == 1 and ewt == 0 and k is None:
                                k = ev
                            elif ef == 2 and ewt == 2:
                                mname = ev.decode("utf-8", "replace")
                if k is not None:
                    meta[k] = mname
        parsed_lines = []
        for lbuf in lines:
            lname, events = "", []
            for lf, lwt, lv in _wire_iter(lbuf):
                if lf == 2 and lwt == 2:
                    lname = lv.decode("utf-8", "replace")
                elif lf == 4 and lwt == 2:
                    mid, dur, off = 0, 0, 0
                    for ef, ewt, ev in _wire_iter(lv):
                        if ef == 1 and ewt == 0:
                            mid = ev
                        elif ef == 2 and ewt == 0:
                            off = ev
                        elif ef == 3 and ewt == 0:
                            dur = ev
                    events.append((meta.get(mid, f"#{mid}"), dur, off))
            parsed_lines.append({"name": lname, "events": events})
        planes.append({"name": name, "lines": parsed_lines})
    return planes


def xplane_device_ms(logdir: str, plane_substr: str = "/device:",
                     by_name: bool = False):
    """Total device-busy milliseconds summed over every *.xplane.pb under
    ``logdir`` for planes whose name contains ``plane_substr`` (XLA device
    planes are '/device:TPU:0'-style; pass '/host:' for host traces). Sums
    top-level event durations per line and takes the busiest line per plane
    (device planes put one op stream per line; nested tracing appears on
    separate lines and must not be double-counted). ``by_name=True`` adds a
    per-event-name breakdown dict."""
    import glob as _glob

    paths = _glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True)
    total_ps = 0
    names: Dict[str, int] = defaultdict(int)
    for p in paths:
        for plane in parse_xplane(p):
            if plane_substr not in plane["name"]:
                continue
            best = 0
            best_events: list = []
            for line in plane["lines"]:
                s = sum(e[1] for e in line["events"])
                if s > best:
                    best, best_events = s, line["events"]
            total_ps += best
            for n, d, _off in best_events:
                names[n] += d
    ms = total_ps / 1e9
    if by_name:
        return ms, {k: v / 1e9 for k, v in
                    sorted(names.items(), key=lambda kv: -kv[1])}
    return ms


def xplane_event_ms(logdir: str, event_name: str,
                    plane_substr: str = "/host:CPU") -> float:
    """Total milliseconds of every event named exactly ``event_name`` across
    ALL lines of matching planes under ``logdir``. The busiest-line heuristic
    of :func:`xplane_device_ms` is right for device planes (one op stream per
    line) but wrong for host planes, where the CPU backend spreads e.g.
    ``ThunkExecutor::Execute`` (its compiled-module execution event) across
    worker-thread lines — the sweep harness uses this as the CPU fallback
    when no device plane exists."""
    import glob as _glob

    total_ps = 0
    for p in _glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                        recursive=True):
        for plane in parse_xplane(p):
            if plane_substr not in plane["name"]:
                continue
            for line in plane["lines"]:
                total_ps += sum(e[1] for e in line["events"]
                                if e[0] == event_name)
    return total_ps / 1e9


def xplane_mapped_ms(logdir: str, resolve) -> Dict[Any, float]:
    """Group device/host-thread event time by ``resolve(event_name) -> key``
    (None = not counted) over every plane/line under ``logdir``, returning
    {key: total ms}. Used by util/cost_model.py with the compiled module's
    instruction→(layer, direction) map, so each HLO-named profiler event
    lands on its layer row.

    Dedup: on one thread line the CPU backend nests spans (a ``call`` thunk
    wraps the fused kernel's own span); only the OUTERMOST *mapped* event of
    any overlap chain is counted, so wrapped kernels are never billed twice.
    The interval walk uses XEvent offsets, which are line-relative — lines
    are independent, which is exactly the granularity needed."""
    import glob as _glob

    totals: Dict[Any, float] = defaultdict(float)
    for p in _glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                        recursive=True):
        for plane in parse_xplane(p):
            for line in plane["lines"]:
                mapped = []
                for name, dur, off in line["events"]:
                    key = resolve(name)
                    if key is not None:
                        # sort key: by start, LONGEST first on ties, so the
                        # outermost event of an equal-offset chain wins
                        mapped.append((off, -dur, key))
                mapped.sort()
                covered_end = -1
                for off, neg_dur, key in mapped:
                    if off >= covered_end:  # outermost of this overlap chain
                        totals[key] += -neg_dur / 1e9
                        covered_end = off - neg_dur
    return dict(totals)


class StepTimer:
    """Whole-train-step Chrome-trace recorder: use as a TrainingListener.
    Produces one 'X' event per iteration (the reference ProfilingListener's
    per-op rows collapse into one fused-step row under XLA — that is the
    point of whole-graph compilation). Wall-clock timebase, shared with the
    OpProfiler and Telemetry exporters (one Perfetto timeline)."""

    def __init__(self):
        self.events: List[dict] = []
        self._last = None

    def iteration_done(self, model, iteration, epoch):
        now = time.time_ns()
        if self._last is not None:
            self.events.append({
                "name": f"train_step[{iteration}]", "ph": "X", "pid": 0,
                "tid": 0, "ts": self._last, "dur": now - self._last,
            })
        self._last = now

    def write_chrome_trace(self, path: str):
        from deeplearning4j_tpu.util.telemetry import trace_epoch_ns

        t0 = trace_epoch_ns()
        if self.events:
            t0 = min(t0, min(e["ts"] for e in self.events))
        out = [dict(e, ts=(e["ts"] - t0) / 1e3, dur=e["dur"] / 1e3)
               for e in self.events]
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
