"""Profiling + correctness guards: op profiler, Chrome trace, NaN panic.

Reference parity (SURVEY.md §5.1–5.2):
- OpProfiler / ProfilerConfig      org/nd4j/linalg/profiler/{OpProfiler,ProfilerConfig}.java
  (per-op wall time + invocation counts, enabled on the executioner via
  profilingConfigurableHookIn/Out)
- ProfilingListener (Chrome trace) org/nd4j/autodiff/listeners/profiler/ProfilingListener.java
- NaN/Inf panic                    OpExecutionerUtil.checkForAny via ProfilerConfig.nanPanic
- PerformanceTracker (bandwidth)   org/nd4j/linalg/memory/PerformanceTracker-style counters

TPU-native notes: under jit there is no per-op host boundary to hook — XLA
fuses the graph — so per-op timing instruments the *eager/by-name* dispatch
path (exec_op), exactly where the reference hooks DefaultOpExecutioner, and
whole-step timing comes from the listeners. For kernel-level depth the JAX
profiler (jax.profiler.trace → TensorBoard/XPlane) is exposed via
``device_trace``; the Chrome-trace exporter writes the same
chrome://tracing JSON the reference's ProfilingListener produces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class ProfilerConfig:
    """ProfilerConfig.java parity."""

    profile_ops: bool = True
    check_for_nan: bool = False      # nanPanic
    check_for_inf: bool = False
    stack_trace: bool = False        # record call sites per op


class OpProfiler:
    """Singleton per-op timing/count profiler (OpProfiler.getInstance parity).

    Wraps the registry's exec_op; use ``start()``/``stop()`` or the
    ``profile()`` context manager. Times are host wall-clock including device
    sync (the honest eager number)."""

    _instance: Optional["OpProfiler"] = None

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.config = config or ProfilerConfig()
        self.reset()
        self._orig_exec = None

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def reset(self):
        self.invocations: Dict[str, int] = defaultdict(int)
        self.total_ns: Dict[str, int] = defaultdict(int)
        self.events: List[dict] = []  # chrome trace events
        self._t0 = time.perf_counter_ns()

    # -- hook ---------------------------------------------------------------
    def start(self):
        """Install the exec hook (profilingHookIn/Out parity)."""
        from deeplearning4j_tpu.ops import registry

        if self._orig_exec is not None:
            return self
        orig = registry.exec_op
        cfg = self.config
        prof = self

        def wrapped(name, *args, **kwargs):
            t0 = time.perf_counter_ns()
            out = orig(name, *args, **kwargs)
            out = jax.block_until_ready(out)
            t1 = time.perf_counter_ns()
            if cfg.profile_ops:
                prof.invocations[name] += 1
                prof.total_ns[name] += t1 - t0
                prof.events.append({
                    "name": name, "ph": "X", "pid": 0, "tid": 0,
                    "ts": (t0 - prof._t0) / 1e3, "dur": (t1 - t0) / 1e3,
                })
            if cfg.check_for_nan or cfg.check_for_inf:
                _panic_check(name, out, cfg)
            return out

        registry.exec_op = wrapped
        self._orig_exec = orig
        return self

    def stop(self):
        from deeplearning4j_tpu.ops import registry

        if self._orig_exec is not None:
            registry.exec_op = self._orig_exec
            self._orig_exec = None
        return self

    @contextlib.contextmanager
    def profile(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()

    # -- reporting ----------------------------------------------------------
    def summary(self) -> str:
        """printOutDashboard parity: per-op totals sorted by time."""
        rows = sorted(self.total_ns.items(), key=lambda kv: -kv[1])
        lines = [f"{'op':<32}{'calls':>8}{'total ms':>12}{'mean us':>12}"]
        for name, ns in rows:
            n = self.invocations[name]
            lines.append(
                f"{name:<32}{n:>8}{ns / 1e6:>12.3f}{ns / 1e3 / max(n, 1):>12.1f}")
        return "\n".join(lines)

    def write_chrome_trace(self, path: str):
        """ProfilingListener parity: chrome://tracing JSON."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events,
                       "displayTimeUnit": "ms"}, f)


class NaNPanicError(FloatingPointError):
    pass


def _panic_check(name, out, cfg):
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if cfg.check_for_nan and np.isnan(arr).any():
            raise NaNPanicError(f"NaN produced by op {name!r} (nanPanic)")
        if cfg.check_for_inf and np.isinf(arr).any():
            raise NaNPanicError(f"Inf produced by op {name!r} (infPanic)")


def check_numerics(tree, where: str = ""):
    """OpExecutionerUtil.checkForAny parity, usable on any pytree (params,
    grads) from user code or listeners."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
            key = jax.tree_util.keystr(path)
            raise NaNPanicError(f"non-finite values at {where}{key}")


@contextlib.contextmanager
def device_trace(logdir: str):
    """Kernel-level device profile via the JAX profiler (TensorBoard/XPlane
    format — the depth the reference never had)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Whole-train-step Chrome-trace recorder: use as a TrainingListener.
    Produces one 'X' event per iteration (the reference ProfilingListener's
    per-op rows collapse into one fused-step row under XLA — that is the
    point of whole-graph compilation)."""

    def __init__(self):
        self.events: List[dict] = []
        self._t0 = time.perf_counter_ns()
        self._last = None

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter_ns()
        if self._last is not None:
            self.events.append({
                "name": f"train_step[{iteration}]", "ph": "X", "pid": 0,
                "tid": 0, "ts": (self._last - self._t0) / 1e3,
                "dur": (now - self._last) / 1e3,
            })
        self._last = now

    def write_chrome_trace(self, path: str):
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events, "displayTimeUnit": "ms"}, f)
