"""Cost attribution: per-layer FLOPs / bytes / device-time accounting + MFU.

PR 4's telemetry answers "is training healthy?"; this layer answers *where*
the FLOPs, bytes, and milliseconds go — the per-op cost-model discipline TVM
(PAPERS.md, arxiv 1802.04799) uses to drive optimization, applied to the
whole-step XLA program. Following the Julia-to-TPU paper's lead, the static
numbers are EXTRACTED FROM THE COMPILATION ARTIFACT itself rather than
re-derived by hand: after ``jit(step).lower().compile()`` (the AOT warmup
path, docs/COMPILE_CACHE.md) the compiled executable exposes

- ``cost_analysis()``   — whole-program FLOPs / transcendentals / bytes,
- ``memory_analysis()`` — argument / output / temp / code buffer sizes,
- ``as_text()``         — the optimized HLO, whose per-instruction
  ``metadata={op_name=...}`` carries the ``jax.named_scope`` path.

The network classes thread ``named_scope("layer:<tag>")`` around every layer
apply (nn/multilayer.py, nn/computation_graph.py), so forward ops surface as
``jvp(layer:<tag>)`` and their backward transposes as
``transpose(jvp(layer:<tag>))`` — one regex recovers (layer, direction) for
every instruction, and a small per-opcode cost model (dot = 2·M·N·K,
convolution = 2·out·kh·kw·ci/g, elementwise = 1 flop/element — XLA's own
HloCostAnalysis conventions) turns the instruction stream into a per-layer
table whose FLOP column sums back to the executable's own
``cost_analysis()`` total (tests assert within 5%).

Runtime attribution reuses the same artifact: the instruction→layer map
built here resolves the HLO-instruction-named XPlane events the JAX
profiler records (util/profiler.py ``xplane_mapped_ms``), yielding a
per-layer fwd/bwd device-time table on real executions.

For backends where ``cost_analysis()``/``as_text()`` are unavailable the
nets fall back to analytic formulas keyed off the layer confs (conv / dense
/ LSTM / attention), and every row carries ``source: xla|analytic`` so
nothing is silently estimated.

Reported via ``net.cost_report()``, the ``/costs`` JSON route
(util/ui_server.py), the ``cost`` group on StatsListener records, and the
``train.examples_per_sec`` / ``train.model_flops_utilization`` telemetry
gauges. MFU = achieved FLOP/s over the ``DL4J_TPU_PEAK_FLOPS`` knob
(config.py). docs/OBSERVABILITY.md#cost-attribution--mfu.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# scope helpers (the contract between the nets and the HLO parser)
# ---------------------------------------------------------------------------

_TAG_BAD = re.compile(r"[^A-Za-z0-9_.\-]")

OPTIMIZER_ROW = "(optimizer)"
UNTAGGED_ROW = "(untagged)"


def sanitize_tag(tag: str) -> str:
    """Layer tags must survive the op_name path verbatim: no '/', no spaces,
    nothing the metadata quoting could mangle."""
    return _TAG_BAD.sub("_", str(tag))


def layer_scope(tag: str):
    """``named_scope`` wrapper every layer apply runs under — trace-time
    only, zero cost in the compiled program."""
    import jax

    return jax.named_scope("layer:" + sanitize_tag(tag))


def optimizer_scope():
    """Scope for the updater loop: optimizer FLOPs (Adam moments etc.) get
    their own row instead of polluting a layer's."""
    import jax

    return jax.named_scope("opt:update")


_LAYER_RE = re.compile(r"layer:([A-Za-z0-9_.\-]+)")


def _resolve_op_name(op_name: str) -> Tuple[Optional[str], str]:
    """(layer tag | OPTIMIZER_ROW | None, 'fwd'|'bwd') from one metadata
    op_name path. Backward ops are the transposed jvp primals."""
    if "opt:update" in op_name:
        return OPTIMIZER_ROW, "fwd"
    m = _LAYER_RE.search(op_name)
    tag = m.group(1) if m else None
    return tag, ("bwd" if "transpose(" in op_name else "fwd")


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%?(\S+?)\s*=\s*(.+)$")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*\))?\s*->.*\{\s*$")
_OPCODE_RE = re.compile(r"^(.+?)\s([a-z][a-zA-Z0-9_\-]*)\((.*)$")
_METADATA_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')
_CALLS_RE = re.compile(r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)")

# XLA HloCostAnalysis conventions: these unary ops count as transcendentals
# (per output element), not flops.
_TRANSCENDENTAL = {
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "rsqrt", "sqrt", "cbrt", "tanh", "sine", "cosine", "tan",
    "atan2", "power", "erf", "expm1",
}
# ...and these count 1 flop per output element (select and convert DO count
# — calibrated against this jaxlib's HloCostAnalysis).
_ELEMENTWISE_FLOP = {
    "add", "subtract", "multiply", "divide", "remainder", "maximum",
    "minimum", "abs", "negate", "sign", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "clamp", "compare",
    "select", "convert", "is-finite", "and", "or", "xor", "not",
    "shift-left", "shift-right-arithmetic", "shift-right-logical",
}
# ops whose cost multiplies by their to_apply reducer computation's per-call
# flops; the reducer bodies themselves are NOT directly counted.
_REDUCERS = {"reduce", "reduce-window", "select-and-scatter", "scatter"}
# computation callers: never cost-counted themselves (their called
# computations' instructions are), but they DO appear as runtime thunk
# events and carry the boundary memory traffic.
_CALLERS = {"fusion", "call", "while", "conditional", "async-start"}
# pure data movement / bookkeeping: zero flops.
_ZERO_FLOP = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "copy", "copy-start", "copy-done", "broadcast",
    "reshape", "transpose", "slice", "concatenate", "pad", "reverse",
    "gather", "dynamic-slice", "dynamic-update-slice", "iota",
    "rng", "rng-bit-generator", "rng-get-and-update-state", "sort",
    "custom-call", "after-all", "partition-id", "replica-id", "domain",
    "optimization-barrier", "infeed", "outfeed", "send", "recv",
    "get-dimension-size",
}


@dataclasses.dataclass
class HloInstr:
    name: str
    opcode: str
    out_elems: int            # total elements across tuple leaves
    out_elems_primary: int    # elements of the first tuple leaf
    out_bytes: int
    operand_elems: List[int]
    operand_bytes: int
    flops: float
    transcendentals: float
    reducer_units: float      # reduce-family: multiplies the reducer's cost
    layer: Optional[str]      # raw tag from own metadata (None if untagged)
    direction: str            # 'fwd' | 'bwd'
    calls: List[str]


def _shapes_of(segment: str) -> List[Tuple[str, List[int]]]:
    out = []
    for dt, dims in _SHAPE_RE.findall(segment):
        if dt not in _DTYPE_BYTES and dt not in ("token", "opaque"):
            continue
        out.append((dt, [int(d) for d in dims.split(",") if d] if dims else []))
    return out


def _elems(dims: List[int]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _bytes_of(shapes: List[Tuple[str, List[int]]]) -> int:
    return sum(_DTYPE_BYTES.get(dt, 0) * _elems(dims) for dt, dims in shapes)


def _split_operands(rest: str) -> Tuple[str, str]:
    """Split the text after ``opcode(`` into (operands, attributes) at the
    matching close paren."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def _dot_flops(out_elems: int, operands: List[Tuple[str, List[int]]],
               attrs: str) -> float:
    """2 * output elements * contracted elements (HloCostAnalysis kDot)."""
    if not operands:
        return 0.0
    lhs = operands[0][1]
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", attrs)
    contracted = 1
    if m and m.group(1):
        for d in m.group(1).split(","):
            i = int(d)
            if i < len(lhs):
                contracted *= lhs[i]
    elif lhs:
        contracted = lhs[-1]
    return 2.0 * out_elems * contracted


def _window_dims(attrs: str, key: str, n: int, default: int) -> List[int]:
    m = re.search(key + r"=([0-9x]+)", attrs)
    if not m:
        return [default] * n
    vals = [int(v) for v in m.group(1).split("x")]
    return vals if len(vals) == n else [default] * n


def _window_pads(attrs: str, n: int) -> List[Tuple[int, int]]:
    m = re.search(r"pad=([0-9_x]+)", attrs)
    if not m:
        return [(0, 0)] * n
    pads = []
    for part in m.group(1).split("x"):
        lo, _, hi = part.partition("_")
        pads.append((int(lo), int(hi or lo)))
    return pads if len(pads) == n else [(0, 0)] * n


def _conv_flops(out_dims: List[int], operands: List[Tuple[str, List[int]]],
                attrs: str) -> float:
    """XLA HloCostAnalysis::HandleConvolution: 2 FLOPs per multiply-add over
    the VALID tap positions only — strided/base-dilated gradient
    convolutions (conv backward under stride > 1) touch a fraction of the
    naive out x kernel-window product, and XLA's total counts exactly that
    fraction; this mirrors its per-spatial-dimension valid-position walk."""
    if len(operands) < 2:
        return 0.0
    lhs, rhs = operands[0][1], operands[1][1]
    m = re.search(r"dim_labels=([^, ]+)", attrs)
    if not m:  # naive fallback: whole kernel at every output element
        kern = 1
        for d in rhs[:-1]:
            kern *= d
        out = 1
        for d in out_dims:
            out *= d
        return 2.0 * out * kern
    spec = m.group(1)
    lhs_spec, rest = spec.split("_", 1)
    rhs_spec, out_spec = rest.split("->")
    nsp = sum(ch.isdigit() for ch in lhs_spec)
    size = _window_dims(attrs, "size", nsp, 1)
    stride = _window_dims(attrs, "stride", nsp, 1)
    lhs_dil = _window_dims(attrs, "lhs_dilate", nsp, 1)
    rhs_dil = _window_dims(attrs, "rhs_dilate", nsp, 1)
    pads = _window_pads(attrs, nsp)
    fgc_m = re.search(r"feature_group_count=(\d+)", attrs)
    bgc_m = re.search(r"batch_group_count=(\d+)", attrs)
    fgc = int(fgc_m.group(1)) if fgc_m else 1
    bgc = int(bgc_m.group(1)) if bgc_m else 1
    valid_total = 1
    for d in range(nsp):
        out_size = out_dims[out_spec.index(str(d))]
        in_size = lhs[lhs_spec.index(str(d))]
        bd, wd = lhs_dil[d], rhs_dil[d]
        pl, _ph = pads[d]
        dilated_in = (in_size - 1) * bd + 1
        cnt = 0
        for ki in range(size[d]):
            kidx = ki * wd
            for o in range(out_size):
                ri = o * stride[d] + kidx - pl
                if ri < 0 or ri >= dilated_in:
                    continue
                if bd > 1 and ri % bd:
                    continue
                cnt += 1
        valid_total *= cnt
    in_feat_per_group = lhs[lhs_spec.index("f")] // max(1, fgc)
    out_feat = out_dims[out_spec.index("f")]
    batch = lhs[lhs_spec.index("b")] // max(1, bgc)
    return 2.0 * in_feat_per_group * out_feat * batch * valid_total


def _instr_costs(opcode: str, out_shapes: List[Tuple[str, List[int]]],
                 out_elems: int, out_primary: int,
                 operands: List[Tuple[str, List[int]]],
                 attrs: str) -> Tuple[float, float, float]:
    """(flops, transcendentals, reducer_units) for one instruction, matching
    XLA's own conventions (calibrated against this jaxlib's HloCostAnalysis)
    closely enough that the module-wide sum lands within the 5%
    reconciliation tolerance (tests/test_cost_model.py). ``reducer_units``
    is the per-reducer-call count for the reduce family: their final flops
    = units x the to_apply computation's per-call cost."""
    if opcode == "dot":
        return _dot_flops(out_elems, operands, attrs), 0.0, 0.0
    if opcode == "convolution":
        out_dims = out_shapes[0][1] if out_shapes else []
        return _conv_flops(out_dims, operands, attrs), 0.0, 0.0
    if opcode in _TRANSCENDENTAL:
        return 0.0, float(out_elems), 0.0
    if opcode in _ELEMENTWISE_FLOP:
        return float(out_elems), 0.0, 0.0
    if opcode == "reduce":
        # variadic reduce: N data operands + N scalar inits
        data = sum(_elems(dims) for _, dims in operands) - len(operands) // 2
        n = max(1, len(operands) // 2)
        return 0.0, 0.0, float(max(0, data // n - out_primary))
    if opcode in ("reduce-window", "select-and-scatter"):
        m = re.search(r"size=([0-9x]+)", attrs)
        win = 1
        if m:
            for d in m.group(1).split("x"):
                win *= int(d)
        return 0.0, 0.0, float(out_primary * max(1, win - 1))
    if opcode == "scatter":
        return 0.0, 0.0, float(
            sum(_elems(d) for _, d in operands[1:]) // 2)
    return 0.0, 0.0, 0.0


def parse_hlo_module(text: str) -> Tuple[Dict[str, List[HloInstr]], str]:
    """Parse one optimized-HLO module text into
    {computation name: [HloInstr]}, plus the entry computation's name."""
    comps: Dict[str, List[HloInstr]] = {}
    cur: Optional[List[HloInstr]] = None
    entry = ""
    for line in text.splitlines():
        if not line.strip():
            continue
        cm = _COMP_RE.match(line)
        if cm:
            cur = comps.setdefault(cm.group(2), [])
            if cm.group(1):
                entry = cm.group(2)
            continue
        if line.strip() == "}":
            cur = None
            continue
        if cur is None:
            continue
        im = _INSTR_RE.match(line)
        if not im:
            continue
        name, rhs = im.group(1), im.group(2)
        om = _OPCODE_RE.match(rhs)
        if not om:
            continue
        type_str, opcode, rest = om.group(1), om.group(2), om.group(3)
        operands_str, attrs = _split_operands(rest)
        out_shapes = _shapes_of(type_str)
        out_elems = sum(_elems(d) for _, d in out_shapes)
        operands = _shapes_of(operands_str)
        mm = _METADATA_RE.search(attrs)
        layer, direction = (None, "fwd")
        if mm:
            layer, direction = _resolve_op_name(mm.group(1))
        out_primary = _elems(out_shapes[0][1]) if out_shapes else 0
        flops, transc, units = (0.0, 0.0, 0.0)
        if opcode not in _CALLERS and opcode not in _ZERO_FLOP:
            flops, transc, units = _instr_costs(
                opcode, out_shapes, out_elems, out_primary, operands, attrs)
        calls = _CALLS_RE.findall(attrs) \
            if (opcode in _CALLERS or opcode in _REDUCERS
                or opcode == "sort") else []
        cur.append(HloInstr(
            name=name, opcode=opcode, out_elems=out_elems,
            out_elems_primary=out_primary,
            out_bytes=_bytes_of(out_shapes),
            operand_elems=[_elems(d) for _, d in operands],
            operand_bytes=_bytes_of(operands),
            flops=flops, transcendentals=transc, reducer_units=units,
            layer=layer, direction=direction, calls=calls))
    return comps, entry


@dataclasses.dataclass
class HloAttribution:
    """Per-layer static costs + the instruction→(layer, dir) map used for
    runtime XPlane grouping."""

    by_layer: Dict[Tuple[str, str], Dict[str, float]]
    flops_total: float
    transcendentals_total: float
    bytes_total: float
    inst_map: Dict[str, Tuple[str, str]]


def attribute_hlo(text: str) -> HloAttribution:
    """Group every instruction's estimated cost by (layer tag, direction).
    Caller instructions (fusion/call/while) are never cost-counted — their
    called computations' bodies are — but they resolve to the majority layer
    of their bodies so byte traffic and runtime thunk events attribute."""
    comps, entry = parse_hlo_module(text)

    # resolve callers bottom-up: a computation's dominant (layer, dir) by
    # flops (then transcendentals, then element count as tie-breakers)
    comp_dom: Dict[str, Tuple[Optional[str], str]] = {}

    def dominant(comp: str, seen=None) -> Tuple[Optional[str], str]:
        if comp in comp_dom:
            return comp_dom[comp]
        seen = seen or set()
        if comp in seen or comp not in comps:
            return (None, "fwd")
        seen.add(comp)
        votes: Dict[Tuple[Optional[str], str], float] = {}
        for ins in comps[comp]:
            key, weight = (ins.layer, ins.direction), \
                (ins.flops + ins.transcendentals + ins.reducer_units
                 + 1e-6 * ins.out_elems)
            if ins.opcode in _CALLERS:
                for callee in ins.calls:
                    ck = dominant(callee, seen)
                    votes[ck] = votes.get(ck, 0.0) + _comp_weight(
                        comps.get(callee, ()))
                continue
            votes[key] = votes.get(key, 0.0) + weight
        tagged = {k: v for k, v in votes.items() if k[0] is not None}
        best = max(tagged or votes or {(None, "fwd"): 0.0},
                   key=lambda k: (tagged or votes).get(k, 0.0))
        comp_dom[comp] = best
        return best

    def _comp_weight(instrs) -> float:
        return sum(i.flops + i.transcendentals + i.reducer_units
                   + 1e-6 * i.out_elems for i in instrs)

    # computations referenced via to_apply (reducers / comparators): their
    # cost is charged at the call site (units x per-call flops), so their
    # bodies — and anything they reach through fusions — must not ALSO be
    # counted directly
    applied: set = set()
    for instrs in comps.values():
        for ins in instrs:
            if ins.opcode not in _CALLERS:
                applied.update(ins.calls)
    stack = list(applied)
    while stack:
        c = stack.pop()
        for ins in comps.get(c, ()):
            for callee in ins.calls:
                if callee not in applied:
                    applied.add(callee)
                    stack.append(callee)

    def per_call_cost(cname: str, seen: Optional[set] = None) -> float:
        """Flops of ONE invocation of a computation, recursing through the
        fusions/calls XLA wraps reducer bodies in."""
        seen = set() if seen is None else seen
        if cname in seen:
            return 0.0
        seen.add(cname)
        total = 0.0
        for i in comps.get(cname, ()):
            if i.opcode in _CALLERS:
                total += sum(per_call_cost(c, seen) for c in i.calls)
            elif i.reducer_units:
                pc = per_call_cost(i.calls[0], seen) if i.calls else 1.0
                total += i.reducer_units * max(1.0, pc)
            else:
                total += i.flops
        return total

    def effective_flops(ins: HloInstr) -> float:
        if ins.reducer_units:
            return ins.reducer_units * max(
                1.0, per_call_cost(ins.calls[0]) if ins.calls else 1.0)
        return ins.flops

    by_layer: Dict[Tuple[str, str], Dict[str, float]] = {}
    inst_map: Dict[str, Tuple[str, str]] = {}
    flops_total = transc_total = bytes_total = 0.0

    def row(layer: Optional[str], direction: str) -> Dict[str, float]:
        key = (layer or UNTAGGED_ROW, direction)
        r = by_layer.get(key)
        if r is None:
            r = by_layer[key] = {"flops": 0.0, "transcendentals": 0.0,
                                 "bytes": 0.0}
        return r

    for cname, instrs in comps.items():
        if cname in applied:
            continue
        for ins in instrs:
            layer, direction = ins.layer, ins.direction
            if ins.opcode in _CALLERS and layer is None:
                # inherit the body's dominant attribution
                doms = [dominant(c) for c in ins.calls] or [(None, "fwd")]
                layer, direction = doms[0]
            if ins.opcode not in _CALLERS:
                eff = effective_flops(ins)
                r = row(layer, direction)
                r["flops"] += eff
                r["transcendentals"] += ins.transcendentals
                flops_total += eff
                transc_total += ins.transcendentals
            # memory traffic is a thunk-boundary quantity: count it on
            # entry-computation instructions only (inner fused ops never
            # touch HBM — that is what fusion is for)
            if cname == entry \
                    and ins.opcode not in ("parameter", "constant", "tuple",
                                           "get-tuple-element"):
                b = ins.out_bytes + ins.operand_bytes
                row(layer, direction)["bytes"] += b
                bytes_total += b
            inst_map[ins.name] = (layer or UNTAGGED_ROW, direction)
    return HloAttribution(by_layer=by_layer, flops_total=flops_total,
                          transcendentals_total=transc_total,
                          bytes_total=bytes_total, inst_map=inst_map)


# ---------------------------------------------------------------------------
# compiled-executable access
# ---------------------------------------------------------------------------


class CostAnalysisUnavailable(RuntimeError):
    """The backend exposes no XLA cost analysis for this executable —
    callers fall back to the analytic formulas (source: analytic)."""


def compiled_totals(compiled) -> Dict[str, float]:
    """Whole-program totals from the executable's own analyses:
    ``cost_analysis()`` (flops / transcendentals / bytes accessed) and
    ``memory_analysis()`` (argument / output / temp / generated code)."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:  # unimplemented on this backend/runtime
        raise CostAnalysisUnavailable(repr(e)) from None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict) or "flops" not in ca:
        raise CostAnalysisUnavailable(f"no flops in cost_analysis: {ca!r}")
    out = {
        "flops": float(ca.get("flops", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                out[k] = int(v)
        out["peak_bytes"] = int(
            getattr(ma, "temp_size_in_bytes", 0)
            + getattr(ma, "argument_size_in_bytes", 0)
            + getattr(ma, "output_size_in_bytes", 0))
    except Exception:
        pass
    return out


def compiled_text(compiled) -> str:
    try:
        text = compiled.as_text()
    except Exception as e:
        raise CostAnalysisUnavailable(repr(e)) from None
    if not text or "ENTRY" not in text:
        raise CostAnalysisUnavailable("no HLO text on this backend")
    return text


# ---------------------------------------------------------------------------
# analytic fallback (source: analytic)
# ---------------------------------------------------------------------------


def analytic_layer_flops(lyr, in_shape, params: int) -> float:
    """Forward FLOPs per EXAMPLE for one layer conf — closed-form formulas
    for the matmul-shaped layers (dense / conv / recurrent / attention),
    a positions·params generic for everything else. in_shape excludes the
    batch dim. Backward is 2x forward (each weight is touched once for dx
    and once for dW — the standard backprop cost model)."""
    cls = type(lyr).__name__
    out_shape = tuple(lyr.output_shape(tuple(in_shape)))
    in_elems = _elems(list(in_shape))
    out_elems = _elems(list(out_shape))
    if cls in ("DenseLayer", "OutputLayer"):
        return 2.0 * in_elems * lyr.n_out
    if cls == "ConvolutionLayer":
        kh, kw = lyr.kernel_size
        c_in = lyr.n_in or in_shape[-1]
        return 2.0 * out_elems * kh * kw * c_in
    if cls == "SeparableConvolution2D":
        kh, kw = lyr.kernel_size
        c_in = lyr.n_in or in_shape[-1]
        pos = out_elems // max(1, out_shape[-1])
        depth = 2.0 * pos * c_in * lyr.depth_multiplier * kh * kw
        point = 2.0 * pos * c_in * lyr.depth_multiplier * lyr.n_out
        return depth + point
    if cls == "Deconvolution2D":
        kh, kw = lyr.kernel_size
        c_in = lyr.n_in or in_shape[-1]
        pos = in_elems // max(1, c_in)
        return 2.0 * pos * kh * kw * c_in * lyr.n_out
    if cls in ("LSTM", "GravesLSTM", "GRU", "SimpleRnn"):
        T = in_shape[0] if len(in_shape) >= 2 else 1
        F = in_shape[-1]
        H = lyr.n_out
        gates = {"LSTM": 4, "GravesLSTM": 4, "GRU": 3, "SimpleRnn": 1}[cls]
        return T * (2.0 * gates * H * (F + H) + 10.0 * H)
    if cls in ("RnnOutputLayer",):
        T = in_shape[0] if len(in_shape) >= 2 else 1
        return 2.0 * T * in_shape[-1] * lyr.n_out
    if "Attention" in cls and hasattr(lyr, "n_heads"):
        S = in_shape[0] if len(in_shape) >= 2 else 1
        D = lyr.n_in or in_shape[-1]
        hd = getattr(lyr, "n_heads", 1) * (getattr(lyr, "head_size", None)
                                           or max(1, lyr.n_out // max(
                                               1, lyr.n_heads)))
        proj = 2.0 * S * D * hd * 3 + 2.0 * S * hd * lyr.n_out
        attn = 4.0 * S * S * hd
        return proj + attn
    if cls == "EmbeddingLayer":
        return 0.0
    if params:
        # generic matmul-dominated estimate: 2 flops per weight per output
        # position (time/spatial positions of the output)
        positions = max(1, out_elems // max(1, out_shape[-1]))
        return 2.0 * params * positions
    return float(out_elems)  # paramless elementwise/pool layers


def analytic_rows(entries, batch: int) -> List["CostRow"]:
    """``entries``: [(tag, layer conf, in_shape excl. batch, param count)].
    Produces the source=analytic table (XLA cost analysis unavailable)."""
    rows = []
    for tag, lyr, in_shape, params in entries:
        fwd = analytic_layer_flops(lyr, in_shape, params) * batch
        out_shape = tuple(lyr.output_shape(tuple(in_shape)))
        byt = 4.0 * (batch * _elems(list(in_shape))
                     + batch * _elems(list(out_shape)) + params)
        rows.append(CostRow(
            layer=sanitize_tag(tag), params=params, flops_fwd=fwd,
            flops_bwd=2.0 * fwd, bytes_accessed=3.0 * byt,
            source="analytic"))
    return rows


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CostRow:
    layer: str
    params: int = 0
    flops_fwd: float = 0.0
    flops_bwd: float = 0.0
    transcendentals: float = 0.0
    bytes_accessed: float = 0.0
    device_time_fwd_s: Optional[float] = None
    device_time_bwd_s: Optional[float] = None
    source: str = "xla"

    @property
    def flops(self) -> float:
        return self.flops_fwd + self.flops_bwd

    @property
    def device_time_s(self) -> Optional[float]:
        if self.device_time_fwd_s is None and self.device_time_bwd_s is None:
            return None
        return (self.device_time_fwd_s or 0.0) + (self.device_time_bwd_s
                                                  or 0.0)

    def to_dict(self) -> dict:
        return {
            "layer": self.layer, "params": self.params,
            "flops_fwd": self.flops_fwd, "flops_bwd": self.flops_bwd,
            "flops": self.flops, "transcendentals": self.transcendentals,
            "bytes_accessed": self.bytes_accessed,
            "device_time_fwd_s": self.device_time_fwd_s,
            "device_time_bwd_s": self.device_time_bwd_s,
            "device_time_s": self.device_time_s,
            "source": self.source,
        }


# canonical dtype keys for the per-dtype peak table; every alias a conf
# compute_dtype or an env author might spell maps to one of these
_PEAK_DTYPE_ALIASES = {
    "bf16": "bf16", "bfloat16": "bf16",
    "fp32": "fp32", "f32": "fp32", "float32": "fp32",
    "fp16": "fp16", "f16": "fp16", "float16": "fp16",
    "int8": "int8", "i8": "int8",
    "fp64": "fp64", "f64": "fp64", "float64": "fp64",
}


def _canon_peak_dtype(dtype) -> Optional[str]:
    if dtype is None:
        return None
    return _PEAK_DTYPE_ALIASES.get(str(dtype).strip().lower())


def peak_flops_from_env(dtype=None) -> Optional[float]:
    """DL4J_TPU_PEAK_FLOPS (config.py): the chip's peak FLOP/s. Accepts a
    bare number (``1.97e14``) or a per-dtype table
    (``bf16=1.97e14,fp32=9.85e13`` — TPU peaks differ ~2x by dtype, so a
    bf16 run must not compute MFU against the fp32 roof). ``dtype`` is the
    run's compute dtype ("bfloat16"/"float32"/... — aliases normalize);
    with a table and no matching entry (or no dtype given) nothing is
    guessed and no MFU is reported. Unset or unparsable → None."""
    v = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if not v or not v.strip():
        return None
    v = v.strip()
    if "=" in v:
        table = {}
        for part in v.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            ck = _canon_peak_dtype(key)
            try:
                f = float(val)
            except ValueError:
                continue
            if ck is not None and f > 0:
                table[ck] = f
        # no dtype: a single-entry table is unambiguous; otherwise fall
        # back to the fp32 entry (the historical bare-number meaning). An
        # UNKNOWN dtype never guesses — no MFU beats a wrong MFU.
        if dtype is None:
            if len(table) == 1:
                return next(iter(table.values()))
            return table.get("fp32")
        ck = _canon_peak_dtype(dtype)
        return None if ck is None else table.get(ck)
    try:
        f = float(v)
    except ValueError:
        return None
    return f if f > 0 else None


@dataclasses.dataclass
class CostReport:
    """Per-layer cost table + whole-step totals + utilization.

    ``devices``: how many devices the analyzed executable spans. XLA's
    ``cost_analysis()`` on a GSPMD-partitioned module reports PER-DEVICE
    totals — ``totals`` (and ``flops_per_step``) keep that per-device
    meaning so the profiled-time reconciliation stays exact, while
    ``totals_global``/``flops_per_step_global`` scale by ``devices`` for
    the whole-program numbers."""

    rows: List[CostRow]
    totals: Dict[str, float]
    batch: int
    params_total: int
    source: str                           # 'xla' | 'analytic'
    model: str = ""
    step_time_s: Optional[float] = None   # measured wall per step
    device_time_s: Optional[float] = None  # attributed device time per step
    peak_flops: Optional[float] = None
    devices: int = 1

    @property
    def flops_per_step(self) -> float:
        return float(self.totals.get("flops", 0.0)) or sum(
            r.flops for r in self.rows)

    @property
    def flops_per_step_global(self) -> float:
        return self.flops_per_step * max(1, self.devices)

    @property
    def totals_global(self) -> Dict[str, float]:
        n = max(1, self.devices)
        return {k: v * n for k, v in self.totals.items()}

    @property
    def examples_per_sec(self) -> Optional[float]:
        if not self.step_time_s:
            return None
        return self.batch / self.step_time_s

    @property
    def achieved_flops_per_sec(self) -> Optional[float]:
        if not self.step_time_s:
            return None
        return self.flops_per_step / self.step_time_s

    @property
    def mfu(self) -> Optional[float]:
        """Model FLOPs utilization: achieved FLOP/s over the configured
        peak (DL4J_TPU_PEAK_FLOPS — per-dtype aware: cost_report() passes
        its conf's compute dtype into peak_flops_from_env). None unless
        both are known."""
        a = self.achieved_flops_per_sec
        if a is None or not self.peak_flops:
            return None
        return a / self.peak_flops

    @property
    def optimizer_update_share(self) -> Optional[float]:
        """Fraction of attributed per-step device time spent in the
        optimizer update phase (the ``(optimizer)`` row from the
        ``opt:update`` scope) — the number the fused donated apply
        (docs/KERNELS.md#fused-optimizer-apply) is built to shrink; gated
        as ``optimizer_update_ms_share`` in benchmarks/regression_gate.py.
        None without a profiled run (``profile=True``)."""
        total = 0.0
        opt = 0.0
        seen = False
        for r in self.rows:
            t = r.device_time_s
            if t is None:
                continue
            seen = True
            total += t
            if r.layer == OPTIMIZER_ROW:
                opt += t
        if not seen or total <= 0.0:
            return None
        return opt / total

    def to_dict(self) -> dict:
        return {
            "model": self.model,
            "batch": self.batch,
            "params_total": self.params_total,
            "source": self.source,
            "totals": dict(self.totals),
            "devices": self.devices,
            "totals_global": self.totals_global,
            "flops_per_step": self.flops_per_step,
            "flops_per_step_global": self.flops_per_step_global,
            "step_time_s": self.step_time_s,
            "device_time_s": self.device_time_s,
            "examples_per_sec": self.examples_per_sec,
            "achieved_flops_per_sec": self.achieved_flops_per_sec,
            "peak_flops": self.peak_flops,
            "model_flops_utilization": self.mfu,
            "optimizer_update_share": self.optimizer_update_share,
            "layers": [r.to_dict() for r in self.rows],
        }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def summary(self) -> str:
        """Human table: one row per layer, totals + MFU footer."""
        def fmt(v, unit=""):
            if v is None:
                return "-"
            if v == 0:
                return "0"
            mag = int(math.floor(math.log10(abs(v)) / 3)) if abs(v) >= 1 \
                else 0
            mag = max(0, min(mag, 5))
            suffix = ["", "K", "M", "G", "T", "P"][mag]
            return f"{v / 1000 ** mag:.2f}{suffix}{unit}"

        lines = [f"{'layer':<34}{'params':>10}{'fwd FLOPs':>12}"
                 f"{'bwd FLOPs':>12}{'bytes':>10}{'t_fwd ms':>10}"
                 f"{'t_bwd ms':>10}  source"]
        for r in self.rows:
            tf = "-" if r.device_time_fwd_s is None \
                else f"{r.device_time_fwd_s * 1e3:.3f}"
            tb = "-" if r.device_time_bwd_s is None \
                else f"{r.device_time_bwd_s * 1e3:.3f}"
            lines.append(
                f"{r.layer:<34}{fmt(r.params):>10}{fmt(r.flops_fwd):>12}"
                f"{fmt(r.flops_bwd):>12}{fmt(r.bytes_accessed):>10}"
                f"{tf:>10}{tb:>10}  {r.source}")
        lines.append(
            f"TOTAL: {fmt(self.flops_per_step)}FLOP/step over B={self.batch}"
            f" ({fmt(float(self.params_total))} params, source={self.source})")
        if self.devices > 1:
            lines.append(
                f"  sharded over {self.devices} devices: totals above are "
                f"PER-DEVICE; global {fmt(self.flops_per_step_global)}"
                "FLOP/step")
        if self.step_time_s:
            lines.append(
                f"  step {self.step_time_s * 1e3:.2f} ms wall -> "
                f"{fmt(self.examples_per_sec)} ex/s, "
                f"{fmt(self.achieved_flops_per_sec)}FLOP/s achieved")
        if self.mfu is not None:
            lines.append(f"  MFU {100.0 * self.mfu:.2f}% of peak "
                         f"{fmt(self.peak_flops)}FLOP/s "
                         "(DL4J_TPU_PEAK_FLOPS)")
        share = self.optimizer_update_share
        if share is not None:
            lines.append(
                f"  optimizer update phase: {100.0 * share:.2f}% of "
                "attributed device time")
        return "\n".join(lines)


def rows_from_attribution(attrib: HloAttribution,
                          params_by_tag: Dict[str, int],
                          layer_times: Optional[Dict[Tuple[str, str], float]]
                          = None) -> List[CostRow]:
    """Merge the HLO attribution with the net's params-per-tag map (tags the
    compiler fused away entirely still get a zero row) and optional runtime
    per-(layer, dir) device seconds."""
    tags: List[str] = list(params_by_tag)
    for (tag, _d) in attrib.by_layer:
        if tag not in tags:
            tags.append(tag)
    if layer_times:
        for (tag, _d) in layer_times:
            if tag not in tags:
                tags.append(tag)
    # deterministic order: net layers first, then optimizer/untagged
    tail = [t for t in (OPTIMIZER_ROW, UNTAGGED_ROW) if t in tags]
    tags = [t for t in tags if t not in tail] + tail
    rows = []
    for tag in tags:
        fwd = attrib.by_layer.get((tag, "fwd"), {})
        bwd = attrib.by_layer.get((tag, "bwd"), {})
        row = CostRow(
            layer=tag, params=params_by_tag.get(tag, 0),
            flops_fwd=fwd.get("flops", 0.0), flops_bwd=bwd.get("flops", 0.0),
            transcendentals=(fwd.get("transcendentals", 0.0)
                             + bwd.get("transcendentals", 0.0)),
            bytes_accessed=fwd.get("bytes", 0.0) + bwd.get("bytes", 0.0),
            source="xla")
        if layer_times is not None:
            row.device_time_fwd_s = layer_times.get((tag, "fwd"), 0.0)
            row.device_time_bwd_s = layer_times.get((tag, "bwd"), 0.0)
        rows.append(row)
    return rows


def layer_times_from_xplane(logdir: str,
                            inst_map: Dict[str, Tuple[str, str]],
                            steps: int = 1) -> Dict[Tuple[str, str], float]:
    """Per-(layer, dir) device seconds for ONE step: group the profiler's
    HLO-instruction-named XPlane events through the compiled module's
    instruction map (outermost-mapped dedup — util/profiler.py), divided by
    the number of traced steps."""
    from deeplearning4j_tpu.util.profiler import xplane_mapped_ms

    def resolve(name: str):
        base = name
        while base.endswith(".clone"):
            base = base[:-len(".clone")]
        base = re.sub(r"\.clone\.\d+$", "", base)
        return inst_map.get(base)

    ms = xplane_mapped_ms(logdir, resolve)
    n = max(1, steps)
    return {key: v / 1e3 / n for key, v in ms.items()}


def profile_compiled_step(compiled, state_args, data_args, steps: int = 3,
                          inst_map: Optional[Dict[str, Tuple[str, str]]]
                          = None):
    """Measure the AOT-compiled train step on COPIES of the live training
    state. The executable donates its state operands, so every call rebinds
    the returned buffers — the model's own params/opt-state are never passed
    in and never invalidated, and the model does not advance.

    Returns ``(step_time_s, layer_times, device_time_s)``: steady-state wall
    seconds per step, and — when ``inst_map`` is given — a JAX-profiler
    traced run grouped per (layer, direction) through the compiled module's
    instruction map (:func:`layer_times_from_xplane`)."""
    import shutil
    import tempfile
    import time as _time

    import jax
    import jax.numpy as jnp

    def copy(t):
        return jax.tree_util.tree_map(jnp.array, t)

    p, s, o, it, key = (copy(a) for a in state_args)

    def run():
        nonlocal p, s, o, it, key
        p, s, o, loss, it, key = compiled(p, s, o, it, key, *data_args)
        return loss

    loss = None
    for _ in range(2):  # warm: the executable is pre-built, this warms caches
        loss = run()
    jax.block_until_ready(loss)
    t0 = _time.perf_counter()
    for _ in range(max(1, steps)):
        loss = run()
    jax.block_until_ready(loss)
    step_time = (_time.perf_counter() - t0) / max(1, steps)
    layer_times = device_time = None
    if inst_map is not None:
        logdir = tempfile.mkdtemp(prefix="dl4j_cost_")
        try:
            jax.profiler.start_trace(logdir)
            try:
                for _ in range(max(1, steps)):
                    loss = run()
                jax.block_until_ready(loss)
            finally:
                jax.profiler.stop_trace()
            layer_times = layer_times_from_xplane(logdir, inst_map,
                                                  max(1, steps))
            device_time = sum(layer_times.values()) or None
        finally:
            shutil.rmtree(logdir, ignore_errors=True)
    return step_time, layer_times, device_time


# ---------------------------------------------------------------------------
# publish registry (the /costs route + StatsListener `cost` group)
# ---------------------------------------------------------------------------

_published: Dict[str, dict] = {}
_published_lock = threading.Lock()


def publish_report(name: str, report: CostReport) -> CostReport:
    """Register a report under ``name`` for the UI server's ``/costs`` route
    and the StatsListener ``cost`` group. Also pushes the utilization
    gauges so /metrics shows them without a fit loop running."""
    with _published_lock:
        _published[str(name)] = report.to_dict()
    from deeplearning4j_tpu.util import telemetry as tm

    if tm.enabled():
        if report.examples_per_sec is not None:
            tm.gauge("train.examples_per_sec", report.examples_per_sec,
                     model=str(name))
        if report.mfu is not None:
            tm.gauge("train.model_flops_utilization", report.mfu,
                     model=str(name))
    return report


def published_reports() -> Dict[str, dict]:
    with _published_lock:
        return {k: dict(v) for k, v in _published.items()}


def clear_published() -> None:
    with _published_lock:
        _published.clear()


def cost_stats_group() -> Optional[dict]:
    """Compact per-report summary for StatsListener records: totals and
    utilization only — the full per-layer table stays on /costs."""
    reps = published_reports()
    if not reps:
        return None
    return {
        name: {
            "flops_per_step": r.get("flops_per_step"),
            "batch": r.get("batch"),
            "params_total": r.get("params_total"),
            "source": r.get("source"),
            "step_time_s": r.get("step_time_s"),
            "examples_per_sec": r.get("examples_per_sec"),
            "model_flops_utilization": r.get("model_flops_utilization"),
            "layers": len(r.get("layers", ())),
        }
        for name, r in reps.items()
    }
