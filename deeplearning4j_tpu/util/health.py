"""Training-health monitors on top of the telemetry registry
(docs/OBSERVABILITY.md).

The reference's training-health surface was the DL4J UI's update:parameter
ratio chart plus ``ProfilerConfig.nanPanic`` — both host-side and both
per-op. Here the monitors run DEVICE-side and piggyback on the coalesced
listener window (docs/HOST_PIPELINE.md): the per-step scores a
:class:`TrainingHealthMonitor` consumes are the ones the
``CoalescingListenerDispatcher`` already fetched in its one-per-window
stacked transfer, and the monitor's own device work (NaN/Inf sentinel +
norm probe) is ONE jitted reduction fetched once per window — no extra
per-step host syncs.

Signals:

- **Loss EWMA bands** — per-step score tracked with an exponentially
  weighted mean/variance; a score outside ``mean ± band_sigma·std`` after
  warmup is a ``loss_anomaly``. Non-finite scores are ``loss_non_finite``.
- **Divergence detection** — the loss EWMA rising past
  ``divergence_factor ×`` its best (minimum) value flags ``divergence``
  (the "loss blew up an order of magnitude" crash signature).
- **Sync-free NaN/Inf sentinel + update-ratio probe** — every ``window``
  iterations one jitted function reduces ``jnp.isfinite`` over every float
  param leaf AND computes ‖params‖ / ‖params − params_prev_window‖; the
  three scalars come back in a single fetch. The update:param ratio (the
  reference chart's quantity, here over a window rather than a single
  step) gets its own EWMA band — a collapsed ratio (vanishing updates) or
  an exploding one both flag ``update_ratio_anomaly``. The previous-window
  param snapshot is a device-side copy (one buffer-sized allocation per
  window; disable with ``update_ratio=False`` on memory-tight chips).
- **HBM gauges** — live/peak device memory from PJRT memory stats, served
  by the registry's scrape-time collector (``/metrics``, StatsListener
  snapshots, and the crash dump in util/stats.py always read the CURRENT
  values — no per-window push needed).

Every anomaly increments ``health.anomalies_total{type=...}``, records an
instant event on the trace timeline, updates the ``/healthz`` registry, and
invokes ``on_anomaly(type, detail)`` if given. ``panic=True`` escalates
non-finite params/scores to :class:`NaNPanicError` (nanPanic parity).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from deeplearning4j_tpu.nn.listeners import TrainingListener
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.profiler import NaNPanicError


class RollbackSignal(RuntimeError):
    """Raised by a monitor with ``action="rollback"`` on a critical anomaly:
    the supervising loop (parallel/elastic.py ElasticTrainer) catches it,
    restores the last good checkpoint, and re-enters training instead of
    letting the run die. Carries the anomaly for the supervisor's log."""

    def __init__(self, kind: str, detail: str, iteration: int):
        super().__init__(f"{kind} at iteration {iteration}: {detail}")
        self.kind = kind
        self.detail = detail
        self.iteration = iteration


#: anomaly kinds worth restoring a checkpoint over — the run's state is
#: poisoned (NaN/Inf) or demonstrably worse than its past self (divergence);
#: band breaches (loss_anomaly, update_ratio_anomaly) only warn
ROLLBACK_KINDS = ("loss_non_finite", "params_non_finite", "divergence")


def record_anomaly(kind: str, detail: str, *, source: str = "health",
                   log=None, on_anomaly=None, **args):
    """The shared anomaly-emission convention: one counter
    (``<source>.anomalies_total{type=kind}``), one instant event on the
    trace timeline (``<source>.anomaly``), optional log line and callback.
    Used by :class:`TrainingHealthMonitor` (source="health") and the SLO
    engine's budget-exhaustion breaches (util/slo.py, source="slo") so
    both speak the same dialect on ``/metrics`` and the merged trace."""
    tm.counter(f"{source}.anomalies_total", type=kind)
    tm.instant(f"{source}.anomaly", type=kind, detail=detail, **args)
    if log is not None:
        log(f"{source.upper()} anomaly: {kind} ({detail})")
    if on_anomaly is not None:
        on_anomaly(kind, detail)


def _finite_and_norms(params, prev):
    """Device-side probe body: [all_finite, ‖params‖, ‖params−prev‖] as one
    stacked float32 vector — three scalars, ONE fetch. ``prev=None`` skips
    the delta term (first window)."""
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(params)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
    if not leaves:
        z = jnp.float32(0)
        return jnp.stack([jnp.float32(1), z, z])
    finite = jnp.array(True)
    for l in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(l)))
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    if prev is None:
        dq = jnp.float32(0)
    else:
        prev_leaves = [l for l in jax.tree_util.tree_leaves(prev)
                       if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
        dq = sum(jnp.sum(jnp.square((a - b).astype(jnp.float32)))
                 for a, b in zip(leaves, prev_leaves))
    return jnp.stack([finite.astype(jnp.float32), jnp.sqrt(sq), jnp.sqrt(dq)])


class _Ewma:
    """Exponentially weighted mean/std with sample counting."""

    __slots__ = ("alpha", "mean", "var", "n", "best")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.best = float("inf")

    def update(self, v: float):
        if self.n == 0:
            self.mean, self.var = v, 0.0
        else:
            a = self.alpha
            d = v - self.mean
            self.mean += a * d
            self.var = (1 - a) * (self.var + a * d * d)
        self.n += 1
        if self.mean < self.best:
            self.best = self.mean

    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))

    def outside_band(self, v: float, sigma: float) -> bool:
        # the std floor is RELATIVE (1% of |mean|): the EWMA variance
        # converges slowly, so an ultra-smooth loss would otherwise flag
        # ordinary jitter as a multi-sigma breach right after warmup
        floor = max(self.std(), 0.01 * abs(self.mean), 1e-12)
        return abs(v - self.mean) > sigma * floor


class TrainingHealthMonitor(TrainingListener):
    """TrainingListener wrapping all the monitors above; install with
    ``net.add_listener(TrainingHealthMonitor())`` (ideally together with
    ``sync_every > 1`` so per-step scores arrive pre-fetched in coalesced
    windows). ``window=None`` derives the probe cadence from the model
    conf's ``sync_every`` (min 10)."""

    def __init__(self, window: Optional[int] = None, alpha: float = 0.05,
                 band_sigma: float = 6.0, divergence_factor: float = 100.0,
                 warmup: int = 20, update_ratio: bool = True,
                 panic: bool = False,
                 on_anomaly: Optional[Callable[[str, str], None]] = None,
                 action: Optional[str] = None,
                 log_fn=print):
        if action not in (None, "rollback"):
            raise ValueError(f"action must be None or 'rollback', got {action!r}")
        self.window = window
        self.alpha = alpha
        self.band_sigma = band_sigma
        self.divergence_factor = divergence_factor
        self.warmup = warmup
        self.update_ratio = update_ratio
        self.panic = panic
        self.on_anomaly = on_anomaly
        self.action = action
        self.log = log_fn
        self.anomalies: list = []  # (iteration, type, detail)
        self._loss = _Ewma(alpha)
        self._ratio = _Ewma(alpha)
        self._probe_fns: dict = {}
        self._copy_fn = None
        self._prev_params = None
        self._last_probe = None  # (finite, param_norm, update_norm)

    # ------------------------------------------------------------- anomalies
    def _anomaly(self, iteration: int, kind: str, detail: str):
        self.anomalies.append((iteration, kind, detail))
        record_anomaly(
            kind, detail, source="health", iteration=iteration,
            log=(lambda _msg: self.log(
                f"HEALTH anomaly at iteration {iteration}: {kind} "
                f"({detail})")) if self.log else None,
            on_anomaly=self.on_anomaly)
        if self.action == "rollback" and kind in ROLLBACK_KINDS:
            # the graceful alternative to panic: the supervising loop
            # restores the last good checkpoint and re-enters training
            raise RollbackSignal(kind, detail, iteration)
        if self.panic and kind in ("loss_non_finite", "params_non_finite"):
            raise NaNPanicError(
                f"training health panic at iteration {iteration}: {kind} "
                f"({detail})")

    def reset(self):
        """Re-arm after an external state change (checkpoint rollback,
        transfer surgery): the EWMA bands and the previous-window param
        snapshot describe a run that no longer exists."""
        self._loss = _Ewma(self.alpha)
        self._ratio = _Ewma(self.alpha)
        self._prev_params = None
        self._last_probe = None

    # ------------------------------------------------------------- listeners
    def iteration_done(self, model, iteration, epoch):
        score = float(model.score_value)
        finite = math.isfinite(score)
        if not finite:
            tm.set_health("training.finite", False,
                          f"non-finite loss at iteration {iteration}")
            self._anomaly(iteration, "loss_non_finite", f"score={score}")
        else:
            ew = self._loss
            if (ew.n > self.warmup
                    and ew.outside_band(score, self.band_sigma)):
                self._anomaly(
                    iteration, "loss_anomaly",
                    f"score={score:.6g} vs ewma={ew.mean:.6g}"
                    f"±{self.band_sigma}·{ew.std():.3g}")
            ew.update(score)
            tm.gauge("health.loss_ewma", ew.mean)
            if (ew.n > self.warmup and ew.best > 0
                    and ew.mean > self.divergence_factor * ew.best):
                tm.set_health(
                    "training.converging", False,
                    f"loss ewma {ew.mean:.6g} is "
                    f">{self.divergence_factor}x its best {ew.best:.6g}")
                self._anomaly(
                    iteration, "divergence",
                    f"ewma={ew.mean:.6g} best={ew.best:.6g}")
            else:
                tm.set_health("training.converging", True, "")
        w = self._window_for(model)
        if iteration % w == 0:
            self._window_probe(model, iteration)

    def _window_for(self, model) -> int:
        if self.window:
            return self.window
        conf = getattr(model, "conf", None)
        return max(10, int(getattr(conf, "sync_every", 1) or 1))

    # ----------------------------------------------------- device-side probe
    def _probe_fn(self, with_prev: bool):
        fn = self._probe_fns.get(with_prev)
        if fn is None:
            import jax

            if with_prev:
                fn = jax.jit(_finite_and_norms)
            else:
                fn = jax.jit(lambda p: _finite_and_norms(p, None))
            self._probe_fns[with_prev] = fn
        return fn

    def _copy(self, params):
        if self._copy_fn is None:
            import jax

            # a*1 forces fresh output buffers (jit identity may alias);
            # the copy is what survives the train step's donation of the
            # live params — a bare reference would be deleted under it
            self._copy_fn = jax.jit(
                lambda t: jax.tree_util.tree_map(lambda a: a * 1, t))
        return self._copy_fn(params)

    def _window_probe(self, model, iteration: int):
        import numpy as np

        params = getattr(model, "params", None)
        if not params:
            return
        with tm.span("health.window_probe", iteration=iteration):
            prev = self._prev_params if self.update_ratio else None
            try:
                if prev is not None:
                    vec = self._probe_fn(True)(params, prev)
                else:
                    vec = self._probe_fn(False)(params)
                finite, pnorm, unorm = (float(v) for v in np.asarray(vec))
            except Exception as e:
                # structure changed mid-run (transfer learning): drop the
                # stale snapshot and re-arm next window — but NEVER
                # silently: a sentinel that died is itself a health event
                self._prev_params = None
                self._probe_fns.clear()
                tm.counter("health.probe_errors_total")
                tm.instant("health.probe_error", iteration=iteration,
                           error=repr(e)[:200])
                if self.log:
                    self.log(f"HEALTH probe error at iteration {iteration}"
                             f" (sentinel re-arming): {e!r}")
                return
            if self.update_ratio:
                self._prev_params = self._copy(params)
        self._last_probe = (bool(finite), pnorm, unorm)
        tm.gauge("health.params_finite", finite)
        tm.gauge("health.param_norm", pnorm)
        if not finite:
            tm.set_health("training.finite", False,
                          f"non-finite params at iteration {iteration}")
            self._anomaly(iteration, "params_non_finite",
                          f"param_norm={pnorm}")
        else:
            tm.set_health("training.finite", True, "")
        if prev is not None and pnorm > 0:
            ratio = unorm / pnorm
            tm.gauge("health.update_ratio", ratio)
            ew = self._ratio
            # ratio == 0 is NOT exempt: an exactly-collapsed window (zero
            # updates — dead ReLUs, lr hit 0, frozen params) is the purest
            # vanishing-update signature and must breach the band
            if ew.n > 3 and ew.outside_band(ratio, self.band_sigma):
                self._anomaly(
                    iteration, "update_ratio_anomaly",
                    f"window update:param ratio {ratio:.3g} vs "
                    f"ewma {ew.mean:.3g}±{self.band_sigma}·{ew.std():.2g}")
            ew.update(ratio)
        # device HBM gauges are served by the registry's scrape-time
        # collector (telemetry.install_default_collectors) — pushing them
        # here too would emit duplicate Prometheus series
        tm.install_default_collectors()

    # ---------------------------------------------------------------- export
    def state(self) -> dict:
        """JSON-able monitor state (tests + crash dump)."""
        return {
            "loss_ewma": self._loss.mean, "loss_ewma_std": self._loss.std(),
            "loss_best": self._loss.best, "iterations_seen": self._loss.n,
            "update_ratio_ewma": self._ratio.mean,
            "last_probe": self._last_probe,
            "anomalies": [
                {"iteration": i, "type": k, "detail": d}
                for i, k, d in self.anomalies[-50:]],
        }
