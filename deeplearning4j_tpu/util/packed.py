"""Packed (flattened) training state — DL4J flattened-params parity.

The reference keeps ALL parameters in one flattened buffer with per-layer
views (BaseMultiLayerUpdater over UpdaterBlocks; `params()` returns the
single array — org/deeplearning4j/nn/multilayer/MultiLayerNetwork.java,
path-cite, mount empty). That design is GPU-era for cheap updater sweeps;
on the remote-TPU path it earns its keep differently: a ResNet-50 train
step carries ~589 device-buffer handles through the tunnel every dispatch
(~4.4 ms/step measured, BASELINE.md). Packing params/states/opt-states into
one buffer per dtype cuts the per-step handle traffic to a handful; inside
the compiled step the buffers are sliced and reshaped back into the pytree
(static offsets — XLA sees ordinary views and keeps its layouts).

Use :class:`PackedTrainer` around an init()ed MultiLayerNetwork or
ComputationGraph; call ``unpack_to_model()`` when you need the model's
pytrees again (evaluation, checkpointing).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util


class StatePacker:
    """Flatten a pytree of arrays into one 1-D buffer per dtype and back.

    Leaf order is the pytree flatten order; offsets are static, so
    ``unpack`` inside jit lowers to slice+reshape views."""

    def __init__(self, template):
        leaves, self.treedef = tree_util.tree_flatten(template)
        self.specs = []
        offsets: dict = {}
        for leaf in leaves:
            arr = jnp.asarray(leaf)
            dt = arr.dtype
            off = offsets.get(dt, 0)
            size = int(np.prod(arr.shape)) if arr.shape else 1
            self.specs.append((dt, off, size, tuple(arr.shape)))
            offsets[dt] = off + size
        self.dtypes = sorted(offsets.keys(), key=str)
        self.sizes = dict(offsets)

    def pack(self, tree) -> Tuple[Any, ...]:
        leaves = tree_util.tree_leaves(tree)
        groups = {dt: [] for dt in self.dtypes}
        for leaf, (dt, _, _, _) in zip(leaves, self.specs):
            groups[dt].append(jnp.ravel(jnp.asarray(leaf)))
        return tuple(jnp.concatenate(groups[dt]) for dt in self.dtypes)

    def unpack(self, buffers):
        bufmap = dict(zip(self.dtypes, buffers))
        leaves = [
            jax.lax.slice(bufmap[dt], (off,), (off + size,)).reshape(shape)
            for dt, off, size, shape in self.specs
        ]
        return tree_util.tree_unflatten(self.treedef, leaves)


class PackedTrainer:
    """Run a model's own train step over packed state buffers.

    Numerically identical to ``model._fit_batch`` (same compiled math,
    different operand packaging — tested in tests/test_packed.py); the win
    is host-side dispatch when the model has hundreds of param leaves.
    """

    def __init__(self, model):
        self.model = model
        if not model.params:
            raise ValueError("model must be init()ed first")
        self.packer = StatePacker(
            (model.params, model.states, model.opt_states))
        self.buffers = self.packer.pack(
            (model.params, model.states, model.opt_states))
        base = model.make_step_fn()
        packer = self.packer

        def step(buffers, iteration, key, inputs, labels):
            params, states, opts = packer.unpack(buffers)
            new_key, sub = jax.random.split(key)
            p, s, o, loss = base(params, states, opts, iteration,
                                 inputs, labels, sub)
            return (packer.pack((p, s, o)), loss, iteration + 1, new_key)

        self._step = jax.jit(step, donate_argnums=(0, 1, 2))
        self._it_dev = jnp.asarray(model.iteration, jnp.int32)
        self.score_value = None

    def _fit_batch(self, x, y):
        m = self.model
        (self.buffers, loss, self._it_dev, m._rng_key) = self._step(
            self.buffers, self._it_dev, m._rng_key, x, y)
        self.score_value = loss
        m.iteration += 1
        return self

    def fit(self, x, y, epochs: int = 1):
        for _ in range(epochs):
            self._fit_batch(x, y)
        return self

    def unpack_to_model(self):
        """Write the packed buffers back into the model's pytrees."""
        params, states, opts = self.packer.unpack(self.buffers)
        m = self.model
        realize = functools.partial(tree_util.tree_map, jnp.asarray)
        m.params, m.states, m.opt_states = (
            realize(params), realize(states), realize(opts))
        # hand back OUR advanced device iteration counter — leaving the
        # model's stale _it_dev in place would make a later plain
        # _fit_batch run Adam bias correction / LR schedules at an old t
        m._it_dev = self._it_dev
        m._it_sync = m.iteration
        if self.score_value is not None:
            m.score_value = self.score_value
        return m
