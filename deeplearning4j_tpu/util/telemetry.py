"""Unified training telemetry: one process-global registry every subsystem
feeds (docs/OBSERVABILITY.md).

PRs 1-3 built three perf subsystems whose wins were visible only through
disjoint instruments — OpProfiler, StepTimer, CompileWatcher, StatsListener
each emitting its own format, and nothing at all observing the mp-ETL worker
processes, the prefetch thread, or ParallelWrapper replicas. This module is
the shared measurement substrate:

- **Counters / gauges / histograms** with optional labels, exported as
  Prometheus text (``/metrics`` on util/ui_server.py) and as a JSON snapshot
  (the ``telemetry`` group in StatsListener records, the crash-report dump).
- **Trace spans** with PID + thread attribution, merged across processes
  into ONE Chrome/Perfetto-loadable trace: fit() dispatch spans (with XLA
  trace/compile sub-spans from the CompileWatcher's jax.monitoring markers),
  prefetch-thread ETL-wait/H2D spans (data/prefetch.py), forked ETL-worker
  chunk spans shipped back over the result pipe (datavec/executor.py), and
  per-replica spans from parallel/wrapper.py. Span timestamps use the WALL
  clock (``time.time_ns``), so events recorded in different processes land
  on one consistent timeline; export normalizes to trace-relative µs.
- **Collectors**: scrape-time callbacks (registered here for the
  CompileWatcher counters, device HBM stats, and the persistent-cache
  entry count) so ``/metrics`` always shows live values without any
  subsystem having to push.
- **Health registry**: util/health.py monitors publish named pass/fail
  checks; ``/healthz`` aggregates them.

Overhead stance: every hook is gated on :func:`enabled` (one attribute
read); a span costs two ``time.time_ns`` calls plus one locked append.
``bench.py telemetry_overhead`` tracks the on/off step-time ratio
(target ≤ 1.05x with all monitors enabled). The span buffer is a bounded
ring (``max_events``) so week-long training cannot leak host memory —
drops are themselves counted (``telemetry.events_dropped_total``).

Env knob: ``DL4J_TPU_TELEMETRY=0`` disables all recording (config.py).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# Default span-ring capacity: ~200 bytes/event -> tens of MB worst case.
_DEFAULT_MAX_EVENTS = 100_000

# Shared trace timebase: EVERY Chrome-trace exporter in the package
# (Telemetry.write_chrome_trace, OpProfiler/StepTimer in util/profiler.py)
# subtracts this one wall-clock origin, so independently written trace files
# load into one Perfetto view on one consistent timeline. Captured at import
# — telemetry is imported before any recording hook can run.
_TRACE_EPOCH_NS = time.time_ns()


def trace_epoch_ns() -> int:
    """The process's shared Chrome-trace time origin (wall ns)."""
    return _TRACE_EPOCH_NS

# Histogram bucket bounds in SECONDS (most observed values are durations);
# exponential-ish ladder from 0.5 ms to 60 s, +Inf implicit.
_DEFAULT_BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _labels_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Hist:
    """One histogram series: bucket counts + sum/count/min/max."""

    __slots__ = ("bounds", "buckets", "count", "sum", "min", "max")

    def __init__(self, bounds=_DEFAULT_BOUNDS):
        self.bounds = bounds
        self.buckets = [0] * (len(bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float):
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        self.buckets[i] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def snapshot(self) -> dict:
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": None if self.count == 0 else round(self.min, 6),
                "max": None if self.count == 0 else round(self.max, 6)}


class Telemetry:
    """Process-global metrics + trace-span registry (singleton via
    :func:`get_telemetry`). All methods are thread-safe; events carry the
    recording thread's id and the process PID, so one registry serves the
    main loop, the prefetch thread, and (after a merge) forked workers."""

    _instance: Optional["Telemetry"] = None
    _instance_lock = threading.Lock()

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS):
        self.enabled = os.environ.get(
            "DL4J_TPU_TELEMETRY", "1").strip().lower() not in (
            "0", "false", "no", "off")
        self.max_events = max_events
        self.counters: Dict[Tuple[str, tuple], float] = {}
        self.gauges: Dict[Tuple[str, tuple], float] = {}
        self.histograms: Dict[Tuple[str, tuple], _Hist] = {}
        self.health: Dict[str, Tuple[bool, str]] = {}
        self._events: deque = deque()
        self._pending: list = []  # event_deferred() staging, GIL-atomic
        self._dropped = 0
        self._collectors: List[Callable[[], list]] = []
        self._lock = threading.Lock()
        self._tls = threading.local()

    @classmethod
    def get_instance(cls) -> "Telemetry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # ----------------------------------------------------------- metrics API
    def counter_inc(self, name: str, value: float = 1.0, **labels):
        if not self.enabled:
            return
        key = (name, _labels_key(labels))
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, **labels):
        if not self.enabled:
            return
        with self._lock:
            self.gauges[(name, _labels_key(labels))] = float(value)

    def observe(self, name: str, value: float, **labels):
        if not self.enabled:
            return
        key = (name, _labels_key(labels))
        with self._lock:
            h = self.histograms.get(key)
            if h is None:
                h = self.histograms[key] = _Hist()
            h.observe(float(value))

    # ------------------------------------------------------------ metric reads
    def counter_total(self, name: str, **label_filter) -> float:
        """Sum of every counter series named ``name`` whose labels are a
        superset of ``label_filter`` (the SLO engine's read path —
        ``counter_total("serving.shed_total", model="dense")`` sums over
        all reasons/lanes of that model)."""
        flt = {str(k): str(v) for k, v in label_filter.items()}.items()
        with self._lock:
            return sum(v for (n, labels), v in self.counters.items()
                       if n == name and flt <= set(labels))

    def gauge_values(self, name: str, **label_filter) -> List[float]:
        """Every gauge value named ``name`` whose labels superset-match
        ``label_filter`` (callers pick max/min for worst/best-case)."""
        flt = {str(k): str(v) for k, v in label_filter.items()}.items()
        with self._lock:
            return [v for (n, labels), v in self.gauges.items()
                    if n == name and flt <= set(labels)]

    # ------------------------------------------------------------- spans API
    def _span_stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, ev: dict):
        """Ring append under the lock; EVERY overflow path (spans, instants,
        merged worker events) syncs the drop counter."""
        if len(self._events) >= self.max_events:
            self._events.popleft()
            self._dropped += 1
            self.counters[("telemetry.events_dropped_total", ())] = \
                self._dropped
        self._events.append(ev)

    def event(self, name: str, t0_ns: int, t1_ns: int, *,
              tid: Optional[Any] = None, tname: Optional[str] = None,
              **args):
        """Record one completed span ('X' event): wall-clock ns endpoints,
        current PID, current thread (or an explicit synthetic ``tid`` —
        parallel/wrapper.py uses one per replica)."""
        if not self.enabled:
            return
        th = threading.current_thread()
        ev = {"name": name, "ph": "X", "pid": os.getpid(),
              "tid": th.ident if tid is None else tid,
              "tname": th.name if tname is None else tname,
              "ts": t0_ns, "dur": max(0, t1_ns - t0_ns)}
        stack = self._span_stack()
        if stack and tid is None:
            args.setdefault("parent", stack[-1])
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def event_deferred(self, name: str, t0_ns: int, t1_ns: int, **args):
        """:meth:`event` minus the registry lock: the record lands on a
        staging list with one GIL-atomic append and is folded into the
        ring at the next export (:meth:`chrome_trace` /
        :meth:`drain_events` / :meth:`snapshot`). For per-request serving
        spans — the registry lock there is GIL time stolen from OTHER
        models' decode loops (the mixed-bench finding: ~20µs/event
        contended vs ~1µs deferred). Ordering across threads is restored
        by Perfetto's ts sort; same-thread order is preserved."""
        if not self.enabled:
            return
        th = threading.current_thread()
        ev = {"name": name, "ph": "X", "pid": os.getpid(), "tid": th.ident,
              "tname": th.name, "ts": t0_ns, "dur": max(0, t1_ns - t0_ns)}
        stack = self._span_stack()
        if stack:
            args.setdefault("parent", stack[-1])
        if args:
            ev["args"] = args
        if len(self._pending) >= self.max_events:  # bound the staging list
            with self._lock:
                self._dropped += 1
                self.counters[("telemetry.events_dropped_total", ())] = \
                    self._dropped
            return
        self._pending.append(ev)

    def _fold_pending(self):
        """Move staged event_deferred() records — plus the serving
        schedulers' staged request spans — into the ring (called under no
        lock; takes the registry lock once for the whole batch)."""
        pend, self._pending = self._pending, []
        pend += _staged_serving_spans()
        if not pend:
            return
        with self._lock:
            for ev in pend:
                self._append(ev)

    def instant(self, name: str, **args):
        """Record a zero-duration marker ('i' event) — stalls, anomalies."""
        if not self.enabled:
            return
        th = threading.current_thread()
        ev = {"name": name, "ph": "i", "pid": os.getpid(), "tid": th.ident,
              "tname": th.name, "ts": time.time_ns(), "s": "t"}
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def span(self, name: str, **args):
        # disabled path returns a shared no-op: zero clock reads, zero
        # allocation beyond this call — the "one attribute read" contract
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    # ------------------------------------------------------ cross-process IO
    def drain_events(self) -> List[dict]:
        """Return + clear the span buffer (forked ETL workers ship the
        result of this over the result pipe; datavec/executor.py)."""
        self._fold_pending()
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def merge_events(self, events) -> int:
        """Merge events recorded in another process/thread (they already
        carry their own PIDs — the wall-clock timebase keeps them on one
        timeline). Returns the number merged."""
        if not events:
            return 0
        with self._lock:
            for ev in events:
                self._append(dict(ev))
        return len(events)

    # --------------------------------------------------------------- health
    def set_health(self, check: str, ok: bool, detail: str = ""):
        with self._lock:
            self.health[check] = (bool(ok), str(detail))

    def health_report(self) -> Tuple[bool, dict]:
        """(all_ok, {check: {"ok": ..., "detail": ...}}); a registry with no
        checks reports healthy (liveness = the process answered)."""
        with self._lock:
            checks = {k: {"ok": v[0], "detail": v[1]}
                      for k, v in self.health.items()}
        return all(c["ok"] for c in checks.values()), checks

    # ----------------------------------------------------------- collectors
    def register_collector(self, fn: Callable[[], list]):
        """``fn() -> [(name, labels_dict, value), ...]`` called at scrape /
        snapshot time; exported as gauges."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _collected(self) -> List[Tuple[str, dict, float]]:
        out = []
        for fn in list(self._collectors):
            try:
                out.extend(fn())
            except Exception:
                continue  # a broken collector must never break a scrape
        return out

    # -------------------------------------------------------------- exports
    def chrome_trace(self) -> dict:
        """Merged Chrome/Perfetto trace JSON: every recorded span (main
        loop + prefetch thread + merged ETL workers + replica rows), ts/dur
        in µs relative to the earliest event, with process/thread name
        metadata rows."""
        self._fold_pending()
        with self._lock:
            events = [dict(e) for e in self._events]
        if not events:
            return {"traceEvents": [], "displayTimeUnit": "ms"}
        # shared timebase with the OpProfiler/StepTimer exporters
        # (util/profiler.py): every trace file subtracts the same origin,
        # so separate files merge onto one Perfetto timeline. Synthetic
        # events older than the epoch (tests) still export consistently.
        t0 = min(trace_epoch_ns(), min(e["ts"] for e in events))
        out: List[dict] = []
        named: set = set()
        mypid = os.getpid()
        for e in events:
            pid, tid = e["pid"], e["tid"]
            if (pid, None) not in named:
                named.add((pid, None))
                role = "main" if pid == mypid else "worker"
                out.append({"name": "process_name", "ph": "M", "pid": pid,
                            "tid": 0,
                            "args": {"name": f"{role} pid={pid}"}})
            if (pid, tid) not in named:
                named.add((pid, tid))
                out.append({"name": "thread_name", "ph": "M", "pid": pid,
                            "tid": tid,
                            "args": {"name": e.get("tname", str(tid))}})
            ev = {"name": e["name"], "ph": e["ph"], "pid": pid, "tid": tid,
                  "ts": (e["ts"] - t0) / 1e3}
            if e["ph"] == "X":
                ev["dur"] = e["dur"] / 1e3
            if e.get("s"):
                ev["s"] = e["s"]
            if e.get("args"):
                ev["args"] = e["args"]
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain version 0.0.4): every
        counter, gauge, histogram, collector output, and health check
        (``dl4j_health_check{check=...}`` 1/0)."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = {k: (h.bounds, list(h.buckets), h.count, h.sum)
                     for k, h in self.histograms.items()}
            health = dict(self.health)
        lines: List[str] = []
        typed: set = set()
        seen_series: set = set()

        def emit(name, labels, value, mtype):
            m = _prom_name(name)
            lab = _prom_labels(labels)
            if (m, lab) in seen_series:
                return  # Prometheus parsers reject duplicate series
            seen_series.add((m, lab))
            if m not in typed:
                typed.add(m)
                lines.append(f"# TYPE {m} {mtype}")
            lines.append(f"{m}{lab} {_prom_num(value)}")

        for (name, labels), v in sorted(counters.items()):
            emit(name, dict(labels), v, "counter")
        for (name, labels), v in sorted(gauges.items()):
            emit(name, dict(labels), v, "gauge")
        # collectors last: a stored gauge with the same name+labels (e.g. a
        # health monitor pushed a device gauge) wins over the scrape-time
        # collector duplicate
        for name, labels, v in self._collected():
            emit(name, labels, v, "gauge")
        for (name, labels), (bounds, buckets, count, total) in \
                sorted(hists.items()):
            m = _prom_name(name)
            if m not in typed:
                typed.add(m)
                lines.append(f"# TYPE {m} histogram")
            cum = 0
            base = dict(labels)
            for b, c in zip(bounds, buckets[:-1]):
                cum += c
                lines.append(
                    f"{m}_bucket{_prom_labels({**base, 'le': repr(b)})} {cum}")
            lines.append(
                f"{m}_bucket{_prom_labels({**base, 'le': '+Inf'})} {count}")
            lines.append(f"{m}_sum{_prom_labels(base)} {_prom_num(total)}")
            lines.append(f"{m}_count{_prom_labels(base)} {count}")
        for check, (ok, _detail) in sorted(health.items()):
            emit("health_check", {"check": check}, 1 if ok else 0, "gauge")
        return "\n".join(lines) + "\n"

    def snapshot(self, events_tail: int = 0) -> dict:
        """JSON-able counters/gauges/histogram-summaries (+ optional last-N
        events) — the StatsListener ``telemetry`` group and the crash dump."""
        if events_tail:
            self._fold_pending()
        with self._lock:
            counters = {_flat_name(k): round(v, 6)
                        for k, v in self.counters.items()}
            gauges = {_flat_name(k): round(v, 6)
                      for k, v in self.gauges.items()}
            hists = {_flat_name(k): h.snapshot()
                     for k, h in self.histograms.items()}
            health = {k: {"ok": v[0], "detail": v[1]}
                      for k, v in self.health.items()}
            tail = [dict(e) for e in list(self._events)[-events_tail:]] \
                if events_tail else []
        for name, labels, v in self._collected():
            gauges[_flat_name((name, _labels_key(labels)))] = v
        out = {"counters": counters, "gauges": gauges,
               "histograms": hists, "health": health}
        if events_tail:
            out["recent_events"] = tail
        return out

    def reset(self):
        _staged_serving_spans()  # discard staged serving request spans
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()
            self.health.clear()
            self._events.clear()
            self._pending = []
            self._dropped = 0
            # collectors survive reset: they are wiring, not data


def _staged_serving_spans() -> list:
    """Request phase spans staged by serving schedulers (cleared on read)
    — sys.modules-guarded like the elastic/serving/tuning collectors, so
    a process that never imported serving pays nothing."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.serving.scheduler")
    if mod is None:
        return []
    try:
        return mod.collect_deferred_spans()
    except Exception:
        return []  # a broken scheduler must never break an export


class _NullSpan:
    """Shared no-op context manager handed out while telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager recording one 'X' event; nesting tracked through a
    thread-local stack so child spans carry ``parent`` attribution."""

    __slots__ = ("_t", "name", "args", "t0")

    def __init__(self, tele: Telemetry, name: str, args: dict):
        self._t = tele
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = time.time_ns()
        self._t._span_stack().append(self.name)
        return self

    def __exit__(self, *exc):
        t1 = time.time_ns()
        stack = self._t._span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self._t.event(self.name, self.t0, t1, **self.args)
        return False


# ---------------------------------------------------------------- module API
def get_telemetry() -> Telemetry:
    return Telemetry.get_instance()


def enabled() -> bool:
    t = Telemetry._instance
    return t.enabled if t is not None else Telemetry.get_instance().enabled


def set_enabled(on: bool) -> None:
    Telemetry.get_instance().enabled = bool(on)


def counter(name: str, value: float = 1.0, **labels):
    Telemetry.get_instance().counter_inc(name, value, **labels)


def gauge(name: str, value: float, **labels):
    Telemetry.get_instance().gauge_set(name, value, **labels)


def observe(name: str, value: float, **labels):
    Telemetry.get_instance().observe(name, value, **labels)


def span(name: str, **args) -> _Span:
    return Telemetry.get_instance().span(name, **args)


def instant(name: str, **args):
    Telemetry.get_instance().instant(name, **args)


def set_health(check: str, ok: bool, detail: str = ""):
    Telemetry.get_instance().set_health(check, ok, detail)


class _StepSpan:
    """Dispatch span with XLA attribution, reusing the CompileWatcher's
    markers: if the dispatch retraced, two sub-spans are emitted whose
    durations come from jax.monitoring (jaxpr trace / backend compile), so
    the merged trace shows WHERE a ragged shape paid compile inside the
    training loop. Costs two counter reads on the hot path."""

    __slots__ = ("name", "args", "_w", "_tr0", "_j0", "_c0", "t0")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args

    def __enter__(self):
        if not enabled():
            self._w = None
            return self
        from deeplearning4j_tpu.util.compile_watcher import get_watcher

        w = self._w = get_watcher()
        self._tr0 = w.total_traces()
        self._j0 = w.jaxpr_trace_seconds
        self._c0 = w.backend_compile_seconds
        self.t0 = time.time_ns()
        return self

    def __exit__(self, *exc):
        w = self._w
        if w is None:
            return False
        t1 = time.time_ns()
        tele = Telemetry.get_instance()
        tele.event(self.name, self.t0, t1, **self.args)
        if w.total_traces() > self._tr0:
            jd = max(0.0, w.jaxpr_trace_seconds - self._j0)
            cd = max(0.0, w.backend_compile_seconds - self._c0)
            tele.counter_inc("xla.step_retraces_total")
            if jd:
                tele.event("xla.jaxpr_trace", self.t0,
                           self.t0 + int(jd * 1e9), parent=self.name)
            if cd:
                c0 = self.t0 + int(jd * 1e9)
                tele.event("xla.backend_compile", c0, c0 + int(cd * 1e9),
                           parent=self.name)
        return False


def step_span(name: str, **args) -> _StepSpan:
    return _StepSpan(name, args)


# ----------------------------------------------------------------- exporters
def _prom_name(name: str) -> str:
    return "dl4j_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        key = re.sub(r"[^a-zA-Z0-9_]", "_", str(k))
        # Prometheus exposition format (text/plain 0.0.4): label values
        # escape backslash, double quote, AND line feed — a raw newline in
        # a value (e.g. a model description) would split the sample line
        # and make the whole scrape unparsable
        val = (str(v).replace("\\", "\\\\").replace('"', '\\"')
               .replace("\n", "\\n"))
        parts.append(f'{key}="{val}"')
    return "{" + ",".join(parts) + "}"


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _flat_name(key: Tuple[str, tuple]) -> str:
    name, labels = key
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


# ---------------------------------------------------------- default sources
_defaults_installed = False
_defaults_lock = threading.Lock()


def install_default_collectors() -> Telemetry:
    """Register the scrape-time sources every deployment wants (idempotent):
    CompileWatcher counters (compile observability), per-device HBM
    live/peak bytes from jax memory stats, persistent-cache entry count."""
    global _defaults_installed
    tele = Telemetry.get_instance()
    with _defaults_lock:
        if _defaults_installed:
            return tele
        tele.register_collector(_collect_compile)
        tele.register_collector(_collect_device_memory)
        tele.register_collector(_collect_compile_cache)
        tele.register_collector(_collect_elastic)
        tele.register_collector(_collect_serving)
        tele.register_collector(_collect_fleet)
        tele.register_collector(_collect_tuning)
        tele.register_collector(_collect_slo)
        _defaults_installed = True
    return tele


def _collect_compile() -> list:
    from deeplearning4j_tpu.util.compile_watcher import CompileWatcher

    w = CompileWatcher._instance
    if w is None:  # never touched: report zeros rather than forcing hooks in
        return [("xla.traces_total", {}, 0), ("xla.backend_compiles_total", {}, 0)]
    c = w.counts()
    return [
        ("xla.traces_total", {}, c["total_traces"]),
        ("xla.backend_compiles_total", {}, c["backend_compiles"]),
        ("xla.uncached_compiles_total", {}, c["uncached_compiles"]),
        ("xla.backend_compile_seconds_total", {}, c["backend_compile_seconds"]),
        ("xla.jaxpr_trace_seconds_total", {}, c["jaxpr_trace_seconds"]),
        ("xla.persistent_cache_hits_total", {}, c["persistent_cache_hits"]),
    ]


def device_memory_stats() -> List[Tuple[str, dict, float]]:
    """Live/peak device memory gauges from PJRT memory stats (HBM on the
    chip; the CPU backend reports allocator stats or nothing). Shared by the
    /metrics collector, util/health.py, and the crash dump."""
    out: List[Tuple[str, dict, float]] = []
    try:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:
                continue
            lab = {"device": str(d.id), "platform": d.platform}
            if "bytes_in_use" in stats:
                out.append(("device.bytes_in_use", lab,
                            float(stats["bytes_in_use"])))
            if "peak_bytes_in_use" in stats:
                out.append(("device.peak_bytes_in_use", lab,
                            float(stats["peak_bytes_in_use"])))
            if "bytes_limit" in stats:
                out.append(("device.bytes_limit", lab,
                            float(stats["bytes_limit"])))
    except Exception:
        pass
    return out


def _collect_device_memory() -> list:
    return device_memory_stats()


def _collect_compile_cache() -> list:
    from deeplearning4j_tpu.util import compile_cache

    d = compile_cache.cache_dir()
    return [("compile_cache.enabled", {}, 1 if d else 0),
            ("compile_cache.entries", {},
             compile_cache.cache_entries() if d else 0)]


def _collect_elastic() -> list:
    """Elastic-runtime membership gauges (world size, live members,
    rollbacks) at scrape time — import-guarded so a process that never
    touched parallel/ pays nothing."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.parallel.elastic")
    if mod is None:
        return []
    return mod.collect_elastic_gauges()


def _collect_serving() -> list:
    """Serving-tier gauges (per-model queue depth, p50/p99 latency, QPS) at
    scrape time — import-guarded like elastic, so a process that never
    served pays nothing (docs/SERVING.md)."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.serving.router")
    if mod is None:
        return []
    return mod.collect_metrics()


def _collect_fleet() -> list:
    """Fleet-tier gauges (ring size, per-worker health/membership/
    in-flight/restarts) at scrape time — import-guarded like serving, so
    a process without a fleet front tier pays nothing
    (docs/SERVING.md#fleet)."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.serving.fleet")
    if mod is None:
        return []
    return mod.collect_metrics()


def _collect_tuning() -> list:
    """Autotuning-database gauges (enabled flag, entry count) at scrape
    time — import-guarded like elastic/serving, so a process that never
    tuned pays nothing (docs/AUTOTUNE.md)."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.tuning.database")
    if mod is None:
        return []
    return mod.collect_tuning_gauges()


def _collect_slo() -> list:
    """SLO gauges (compliance, burn rates, budget remaining) at scrape
    time — import-guarded like elastic/serving/tuning, so a process that
    never declared an objective pays nothing (docs/OBSERVABILITY.md)."""
    import sys

    mod = sys.modules.get("deeplearning4j_tpu.util.slo")
    if mod is None:
        return []
    return mod.collect_slo_gauges()


def _after_fork_child():
    """Forked children (mp-ETL workers) inherit the parent's registry by
    memory image: re-arm the lock (the parent may have held it mid-fork)
    and clear inherited spans so a worker ships only its OWN events — its
    PID attribution is then correct by construction."""
    t = Telemetry._instance
    if t is not None:
        t._lock = threading.Lock()
        t._tls = threading.local()
        t._events = deque()
        t._pending = []
        t._dropped = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_after_fork_child)
