"""CompileWatcher — recompile observability for the compile-once subsystem.

XLA recompilation is the systematic cost this layer makes visible: every
ragged last batch, TBPTT remainder, eval batch size, and fresh process pays
a full trace+compile unless shape bucketing / the persistent compilation
cache / AOT warmup (docs/COMPILE_CACHE.md) absorbs it. The reference-era
analogue is cuDNN algo re-selection on shape change (``cudnnAlgoMode``);
here the unit of waste is a whole XLA program.

Two complementary signals are collected:

- **Traces, per function with per-shape attribution** — the network/session
  classes call :func:`note_trace` INSIDE their to-be-jitted step/forward
  bodies. The Python body only executes while JAX is tracing, so each call
  is exactly one retrace of that function, and the abstract shapes of the
  traced arguments say which input signature caused it. Zero overhead on
  the compiled hot path (the call does not exist in the jitted program).
- **Backend compiles + persistent-cache hits, process-global** — via
  ``jax.monitoring`` events (``/jax/core/compile/backend_compile_duration``,
  ``/jax/compilation_cache/cache_hits``). These count every XLA compile in
  the process including sub-jits, and how many were served from the on-disk
  cache (util/compile_cache.py).

Surfaced through ``RecompileListener`` (nn/listeners.py), the StatsListener
``compile`` record group (util/stats.py), ``bench.py recompile_overhead``
and ``benchmarks/compile_cache_sweep.py``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_TRACE_DUR = "/jax/core/compile/jaxpr_trace_duration"
_CACHE_HIT = "/jax/compilation_cache/cache_hits"

_listeners_installed = False
_install_lock = threading.Lock()


def _install_monitoring_listeners():
    """Register the jax.monitoring hooks ONCE per process (jax.monitoring has
    no per-listener removal) and forward into the live singleton, so
    reset()/replacement keeps working."""
    global _listeners_installed
    with _install_lock:
        if _listeners_installed:
            return
        import jax.monitoring as monitoring

        def on_event(event, **kw):
            w = CompileWatcher._instance
            if w is not None and event == _CACHE_HIT:
                w.persistent_cache_hits += 1

        def on_duration(event, duration, **kw):
            w = CompileWatcher._instance
            if w is None:
                return
            if event == _BACKEND_COMPILE:
                w.backend_compiles += 1
                w.backend_compile_seconds += duration
            elif event == _TRACE_DUR:
                w.jaxpr_trace_seconds += duration

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
        _listeners_installed = True


def _shape_of(x) -> Any:
    """Abstract signature of one traced argument (works on tracers, arrays,
    None, and nested lists/dicts — kept shallow and cheap)."""
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return tuple(_shape_of(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _shape_of(v)) for k, v in x.items()))
    shape = getattr(x, "shape", None)
    if shape is None:
        return type(x).__name__
    return (tuple(shape), str(getattr(x, "dtype", "?")))


class CompileWatcher:
    """Counts traces/compiles per function with per-shape attribution.

    Use the process singleton (:meth:`get_instance` / module-level
    :func:`get_watcher`); instruments call :func:`note_trace` at trace time.
    ``scope()`` gives delta-counting for tests and harnesses."""

    _instance: Optional["CompileWatcher"] = None

    def __init__(self):
        self.traces: Dict[str, int] = {}
        self.shapes: Dict[str, Dict[Any, int]] = {}
        self.events: List[Tuple[float, str, Any]] = []  # (wall_s, fn, sig)
        self.backend_compiles = 0
        self.backend_compile_seconds = 0.0
        self.jaxpr_trace_seconds = 0.0
        self.persistent_cache_hits = 0
        self._lock = threading.Lock()
        # per-thread trace tally: note_trace runs ON the thread that
        # triggered the trace (jit tracing is synchronous), so this lets a
        # serving worker count only the traces ITS batches caused — a
        # rolling reload's shadow warmup compiling on another thread must
        # not show up as steady-state serving recompiles (serving/model.py)
        self._tls = threading.local()

    @classmethod
    def get_instance(cls) -> "CompileWatcher":
        if cls._instance is None:
            cls._instance = cls()
        _install_monitoring_listeners()
        return cls._instance

    # ------------------------------------------------------------- recording
    def note_trace(self, fn_name: str, *traced_args) -> None:
        sig = tuple(_shape_of(a) for a in traced_args)
        self._tls.traces = getattr(self._tls, "traces", 0) + 1
        with self._lock:
            self.traces[fn_name] = self.traces.get(fn_name, 0) + 1
            per = self.shapes.setdefault(fn_name, {})
            per[sig] = per.get(sig, 0) + 1
            self.events.append((time.time(), fn_name, sig))

    # --------------------------------------------------------------- queries
    def total_traces(self) -> int:
        return sum(self.traces.values())

    def thread_traces(self) -> int:
        """Traces noted on the CALLING thread since it first traced (0 for
        a thread that never did). Delta this around a region to count only
        the traces that region itself caused — immune to concurrent
        compilation on other threads (a reload's shadow warmup, another
        model's cold start)."""
        return getattr(self._tls, "traces", 0)

    def counts(self) -> Dict[str, Any]:
        """One JSON-able snapshot of every counter. ``uncached_compiles``
        subtracts persistent-cache hits from the backend-compile event count:
        jax emits ``backend_compile_duration`` even when the executable is
        deserialized from the on-disk cache, so the raw count alone does not
        drop on a warm process — the difference is what actually recompiled."""
        return {
            "traces": dict(self.traces),
            "total_traces": self.total_traces(),
            "backend_compiles": self.backend_compiles,
            "uncached_compiles": max(
                0, self.backend_compiles - self.persistent_cache_hits),
            "backend_compile_seconds": round(self.backend_compile_seconds, 4),
            "jaxpr_trace_seconds": round(self.jaxpr_trace_seconds, 4),
            "persistent_cache_hits": self.persistent_cache_hits,
        }

    def summary(self) -> str:
        lines = [
            f"CompileWatcher: {self.total_traces()} traces, "
            f"{self.backend_compiles} backend compiles "
            f"({self.backend_compile_seconds:.2f}s), "
            f"{self.persistent_cache_hits} persistent-cache hits"
        ]
        for fn in sorted(self.traces):
            lines.append(f"  {fn}: {self.traces[fn]} trace(s)")
            for sig, n in self.shapes.get(fn, {}).items():
                lines.append(f"    x{n}  {sig}")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self.traces.clear()
            self.shapes.clear()
            self.events.clear()
            self.backend_compiles = 0
            self.backend_compile_seconds = 0.0
            self.jaxpr_trace_seconds = 0.0
            self.persistent_cache_hits = 0

    def scope(self) -> "CompileScope":
        """Delta counter: ``with watcher.scope() as s: ...; s.traces``."""
        return CompileScope(self)


class CompileScope:
    """Counts traces/compiles between ``__enter__`` and the read point —
    the regression-test primitive (``assert scope.traces == N``)."""

    def __init__(self, watcher: CompileWatcher):
        self.watcher = watcher
        self._t0: Dict[str, int] = {}
        self._c0 = 0
        self._h0 = 0

    def __enter__(self) -> "CompileScope":
        self._t0 = dict(self.watcher.traces)
        self._c0 = self.watcher.backend_compiles
        self._h0 = self.watcher.persistent_cache_hits
        return self

    def __exit__(self, *exc):
        return False

    @property
    def traces(self) -> int:
        return sum(
            n - self._t0.get(fn, 0) for fn, n in self.watcher.traces.items()
        )

    def traces_of(self, fn_name: str) -> int:
        return self.watcher.traces.get(fn_name, 0) - self._t0.get(fn_name, 0)

    @property
    def backend_compiles(self) -> int:
        return self.watcher.backend_compiles - self._c0

    @property
    def persistent_cache_hits(self) -> int:
        return self.watcher.persistent_cache_hits - self._h0


def get_watcher() -> CompileWatcher:
    """The process CompileWatcher (installs monitoring hooks on first use)."""
    return CompileWatcher.get_instance()


def note_trace(fn_name: str, *traced_args) -> None:
    """Record one retrace of ``fn_name`` — call INSIDE the function handed to
    ``jax.jit``; executes only while tracing, never in the compiled program."""
    CompileWatcher.get_instance().note_trace(fn_name, *traced_args)
