"""Fault injection + uniform retry/backoff policy (docs/FAULT_TOLERANCE.md).

The reference's failure story is Spark partition retry plus
CrashReportingUtil: every worker-side failure either retries bounded-many
times or surfaces loudly. This module is the TPU-native equivalent's shared
substrate, used by the elastic runtime (parallel/elastic.py), the
multiprocess ETL executor (datavec/executor.py), the prefetch pipeline
(data/prefetch.py), checkpoint I/O (util/checkpoint.py), and the DCN
bootstrap handshake (parallel/distributed.py):

- :class:`RetryPolicy` — ONE policy object (exponential backoff + jitter +
  overall deadline) everywhere a transient failure is retried, replacing
  the previous one-shot timeouts. Every retry increments
  ``elastic.retries_total{op=...}`` so post-mortems can see which seams
  flapped before a run died.
- :class:`FaultInjector` — a process-global registry of injectable faults
  (kill an ETL worker, stall the prefetch producer, drop heartbeats,
  poison a batch with NaN, SIGKILL the host, and — since ISSUE 13 — the
  serving-path kinds: fail a batch's compute, crash a scheduler worker,
  stall a batch, corrupt a reload archive), each triggerable at a step
  number programmatically or via the ``DL4J_TPU_FAULTS`` env knob
  (``"inject_nan@5,kill_etl_worker"``). Recovery code that cannot be
  made to fire in a test does not ship — tests/test_elastic.py and the
  benchmarks/fault_smoke.py CI leg drive every kind through its recovery
  path.

Injection sites are ordinary production code paths: each site asks
``get_injector().fire(kind, step)`` (a dict lookup when no faults are
armed — zero overhead in real runs) and simulates the failure *mechanism*
(SIGKILL the real worker process, sleep the real producer thread), so the
recovery path exercised is the one a real fault would take.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.util import telemetry as tm


class RetryExhaustedError(RuntimeError):
    """A retried operation failed on every attempt (or hit its deadline).
    ``__cause__`` carries the final underlying exception."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + full jitter + overall deadline.

    ``max_attempts``: total tries (1 = no retry). ``base_delay`` doubles
    (``multiplier``) per retry, capped at ``max_delay``; each sleep is
    scaled by a uniform ``[1-jitter, 1]`` draw so N workers retrying the
    same dead coordinator do not thundering-herd in lockstep.
    ``deadline``: overall wall-clock budget in seconds across ALL attempts
    (None = unbounded); a retry that would start past the deadline raises
    instead of sleeping.
    """

    max_attempts: int = 3
    base_delay: float = 0.1
    multiplier: float = 2.0
    max_delay: float = 5.0
    jitter: float = 0.25
    deadline: Optional[float] = None

    def delays(self) -> List[float]:
        """Backoff schedule between attempts (len == max_attempts - 1)."""
        out, d = [], self.base_delay
        for _ in range(max(0, self.max_attempts - 1)):
            out.append(min(d, self.max_delay))
            d *= self.multiplier
        return out

    def with_(self, **kw) -> "RetryPolicy":
        return replace(self, **kw)

    def sleep_before_retry(self, attempt: int) -> float:
        """Jittered backoff before retry number ``attempt`` (1-based) —
        for callers that drive their own retry loop (the mp-ETL chunk
        supervisor) but must keep this policy's backoff semantics. Returns
        the seconds slept."""
        delays = self.delays()
        if not delays:
            return 0.0
        d = delays[min(attempt - 1, len(delays) - 1)]
        d *= 1.0 - self.jitter * random.random()
        time.sleep(d)
        return d

    def run(self, fn: Callable, *, name: str = "op",
            retry_on: tuple = (Exception,),
            on_retry: Optional[Callable[[int, BaseException], None]] = None):
        """Call ``fn()`` under this policy. Transient failures (``retry_on``)
        back off and retry; exhaustion raises :class:`RetryExhaustedError`
        from the last failure. Never swallows KeyboardInterrupt/SystemExit."""
        t0 = time.monotonic()
        delays = self.delays()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 — retry loop by design
                last = e
                if attempt >= self.max_attempts - 1:
                    break
                delay = delays[attempt] * (1.0 - self.jitter * random.random())
                if (self.deadline is not None
                        and time.monotonic() - t0 + delay > self.deadline):
                    raise RetryExhaustedError(
                        f"{name}: deadline {self.deadline}s exhausted after "
                        f"{attempt + 1} attempt(s): {type(e).__name__}: {e}"
                    ) from e
                tm.counter("elastic.retries_total", op=name)
                tm.instant("elastic.retry", op=name, attempt=attempt + 1,
                           error=f"{type(e).__name__}: {e}"[:200])
                if on_retry is not None:
                    on_retry(attempt + 1, e)
                time.sleep(delay)
        raise RetryExhaustedError(
            f"{name}: failed after {self.max_attempts} attempt(s): "
            f"{type(last).__name__}: {last}") from last


# --------------------------------------------------------------------- faults
#: fault kinds and the site that consumes each one
KILL_ETL_WORKER = "kill_etl_worker"    # datavec/executor.py: SIGKILL a child
STALL_PREFETCH = "stall_prefetch"      # data/prefetch.py: producer sleeps
DROP_HEARTBEAT = "drop_heartbeat"      # parallel/elastic.py: skip heartbeats
INJECT_NAN = "inject_nan"              # parallel/elastic.py: poison a batch
SIGKILL_HOST = "sigkill_host"          # parallel/elastic.py: kill this process
# serving-path kinds (docs/SERVING.md#resilience): the r13 tier's failure
# modes, each firing on the REAL mechanism so the recovery exercised is the
# production one (benchmarks/resilience_smoke.py drives all four in CI)
SERVING_COMPUTE_ERROR = "serving_compute_error"  # serving/model.py: execute raises
SERVING_WORKER_CRASH = "serving_worker_crash"    # serving/scheduler.py: worker loop dies
SERVING_SLOW_BATCH = "serving_slow_batch"        # serving/model.py: execute stalls arg ms
RELOAD_CORRUPT_ARCHIVE = "reload_corrupt_archive"  # serving/router.py: reload reads a truncated zip

FAULT_KINDS = (KILL_ETL_WORKER, STALL_PREFETCH, DROP_HEARTBEAT, INJECT_NAN,
               SIGKILL_HOST, SERVING_COMPUTE_ERROR, SERVING_WORKER_CRASH,
               SERVING_SLOW_BATCH, RELOAD_CORRUPT_ARCHIVE)

#: kinds whose injection site has a step concept — the elastic training
#: loop's iteration for inject_nan/sigkill_host, the serving scheduler's
#: batch-cycle sequence number for the serving_* kinds (``@nth`` = fire at
#: the nth batch the worker runs). The other sites — the ETL dispatcher,
#: the prefetch producer, the heartbeat thread, the reload path — fire with
#: step=None, where a step-gated fault stays armed forever, so @step is
#: rejected for them at parse/inject time ("a typo'd chaos knob must not
#: silently test nothing")
STEP_GATED_KINDS = (INJECT_NAN, SIGKILL_HOST, SERVING_COMPUTE_ERROR,
                    SERVING_WORKER_CRASH, SERVING_SLOW_BATCH)


@dataclass
class Fault:
    """One armed fault. ``at_step=None`` fires at the first opportunity;
    ``count`` is how many times it fires before disarming (-1 = forever).
    ``arg`` is kind-specific (stall seconds, heartbeats to drop)."""

    kind: str
    at_step: Optional[int] = None
    count: int = 1
    arg: Optional[float] = None
    fired: int = field(default=0, compare=False)

    def should_fire(self, step: Optional[int]) -> bool:
        if self.count >= 0 and self.fired >= self.count:
            return False
        if self.at_step is None:
            return True
        # sites without a step concept (prefetch producer, heartbeat
        # thread) pass step=None: a step-gated fault stays armed for them
        return step is not None and step >= self.at_step


class FaultInjector:
    """Process-global fault registry (singleton via :func:`get_injector`).

    Arm programmatically::

        get_injector().inject(INJECT_NAN, at_step=5)

    or from the environment (read once at first access)::

        DL4J_TPU_FAULTS="kill_etl_worker,inject_nan@5,stall_prefetch:3.0"

    where ``kind[@step][:arg]``. Sites call :meth:`fire`, which consumes
    one firing and records ``faults.injected_total{kind=...}``.
    """

    _instance: Optional["FaultInjector"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: Dict[str, List[Fault]] = {}
        self.log: List[Tuple[str, Optional[int]]] = []  # (kind, step) fired
        for f in parse_fault_spec(os.environ.get("DL4J_TPU_FAULTS", "")):
            self._faults.setdefault(f.kind, []).append(f)
        #: lock-free fast path for fire() — the serving tier calls fire()
        #: on every batch cycle (util/faults is process-global), and an
        #: un-chaos'd process must not pay a global lock acquisition per
        #: call. Conservative: set on inject, cleared only by clear()
        #: (a process with exhausted faults is a chaos test already).
        self._armed_fast = bool(self._faults)

    @classmethod
    def get_instance(cls) -> "FaultInjector":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    def inject(self, kind: str, at_step: Optional[int] = None,
               count: int = 1, arg: Optional[float] = None) -> Fault:
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"one of {FAULT_KINDS}")
        if at_step is not None and kind not in STEP_GATED_KINDS:
            raise ValueError(
                f"fault kind {kind!r} fires at a site with no step concept;"
                f" @step would arm a fault that can never fire (step-gated "
                f"kinds: {STEP_GATED_KINDS})")
        f = Fault(kind, at_step=at_step, count=count, arg=arg)
        with self._lock:
            self._faults.setdefault(kind, []).append(f)
            self._armed_fast = True
        return f

    def armed(self, kind: Optional[str] = None) -> bool:
        with self._lock:
            kinds = [kind] if kind else list(self._faults)
            return any(f.count < 0 or f.fired < f.count
                       for k in kinds for f in self._faults.get(k, ()))

    def fire(self, kind: str, step: Optional[int] = None) -> Optional[Fault]:
        """Consume one firing of ``kind`` at ``step`` (None when the site has
        no step concept). Returns the Fault (for ``arg``) or None."""
        if not self._armed_fast:  # plain attribute read: no lock on the
            return None           # hot path of an un-chaos'd process
        with self._lock:
            for f in self._faults.get(kind, ()):
                if f.should_fire(step):
                    f.fired += 1
                    self.log.append((kind, step))
                    break
            else:
                return None
        tm.counter("faults.injected_total", kind=kind)
        tm.instant("faults.injected", kind=kind,
                   step=-1 if step is None else step)
        return f

    def clear(self):
        with self._lock:
            self._faults.clear()
            self.log.clear()
            self._armed_fast = False


def parse_fault_spec(spec: str) -> List[Fault]:
    """``"kill_etl_worker,inject_nan@5,stall_prefetch:3.0"`` ->
    [Fault, ...]. Unknown kinds raise, and ``@step`` on a kind whose
    site has no step concept raises (a typo'd chaos knob must not
    silently test nothing)."""
    out: List[Fault] = []
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        arg: Optional[float] = None
        if ":" in part:
            part, args = part.split(":", 1)
            arg = float(args)
        if "@" in part:
            kind, steps = part.split("@", 1)
            at_step: Optional[int] = int(steps)
        else:
            kind, at_step = part, None
        kind = kind.strip().lower()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"DL4J_TPU_FAULTS: unknown fault kind {kind!r}; "
                f"one of {FAULT_KINDS}")
        if at_step is not None and kind not in STEP_GATED_KINDS:
            raise ValueError(
                f"DL4J_TPU_FAULTS: {kind!r} fires at a site with no step "
                f"concept — drop the @{at_step} (step-gated kinds: "
                f"{STEP_GATED_KINDS})")
        out.append(Fault(kind, at_step=at_step, arg=arg))
    return out


def get_injector() -> FaultInjector:
    return FaultInjector.get_instance()
