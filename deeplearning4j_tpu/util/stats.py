"""Stats pipeline: StatsListener → StatsStorage, and crash reporting.

Reference parity (SURVEY.md §5.5, §2.2 J19):
- StatsListener / StatsStorage   deeplearning4j-ui-model .../stats/StatsListener.java,
  storage impls InMemoryStatsStorage / FileStatsStorage (MapDB) / remote.
- CrashReportingUtil             org/deeplearning4j/util/CrashReportingUtil.java
  (memory/config dump on OOM).

The Vert.x web UI itself is out of scope (a browser dashboard, not a
framework capability); the storage format is line-JSON so any plotting tool
— or the included ``to_csv`` — renders training curves. The listener records
the same content groups as the reference: score, per-layer parameter /
update / activation summary statistics (mean, std, min, max, norm), timing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np


def _summary(arr, bins: int = 0) -> Dict[str, float]:
    a = np.asarray(arr, np.float64)
    if a.size == 0:
        # size-0 leaves (empty embedding slices, 0-row batches) must not
        # crash the listener: np.min/np.max on empty raise, np.mean warns
        # and returns nan. NaN-safe summary; l2 of nothing is exactly 0.
        return {"mean": float("nan"), "std": float("nan"),
                "min": float("nan"), "max": float("nan"), "l2": 0.0}
    out = {
        "mean": float(a.mean()), "std": float(a.std()),
        "min": float(a.min()), "max": float(a.max()),
        "l2": float(np.linalg.norm(a)),
    }
    if bins:
        # histogram bins for the UI histogram pages (DL4J model-page
        # parameter/update histograms); non-finite values would make
        # np.histogram's range computation raise
        flat = a.ravel()
        finite = flat[np.isfinite(flat)]
        if finite.size:
            counts, edges = np.histogram(finite, bins=bins)
            out["hist"] = [int(c) for c in counts]
            out["hist_range"] = [float(edges[0]), float(edges[-1])]
    return out


class InMemoryStatsStorage:
    """InMemoryStatsStorage parity: records kept in a list."""

    def __init__(self):
        self.records: List[dict] = []

    def put(self, record: dict):
        self.records.append(record)

    def sessions(self):
        return sorted({r["session"] for r in self.records})

    def scores(self, session=None):
        return [(r["iteration"], r["score"]) for r in self.records
                if session is None or r["session"] == session]


class FileStatsStorage(InMemoryStatsStorage):
    """FileStatsStorage parity: append-only line-JSON file."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                self.records = [json.loads(ln) for ln in f if ln.strip()]

    def put(self, record: dict):
        super().put(record)
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


def to_csv(storage, path: str):
    """Training-curve export (the UI-chart replacement)."""
    with open(path, "w") as f:
        f.write("session,iteration,epoch,score,iter_ms\n")
        for r in storage.records:
            f.write(f"{r['session']},{r['iteration']},{r.get('epoch', '')},"
                    f"{r['score']},{r.get('iter_ms', '')}\n")


class StatsListener:
    """StatsListener parity: push score + per-layer param/update stats to a
    StatsStorage every ``frequency`` iterations."""

    def __init__(self, storage, frequency: int = 1, session_id: Optional[str] = None,
                 collect_histograms: bool = True, histogram_bins: int = 30,
                 collect_activations: bool = False):
        self.storage = storage
        self.frequency = frequency
        self.session_id = session_id or f"session_{int(time.time())}"
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        # DL4J's model page also charts per-layer ACTIVATION stats; costs an
        # extra forward per logged iteration, so opt-in
        self.collect_activations = collect_activations
        self._last_ns = None
        self._prev_params = None

    def iteration_done(self, model, iteration, epoch):
        # push-time stamp under coalesced (sync_every>1) dispatch — at flush
        # the callbacks run back-to-back, so perf_counter here would report
        # near-zero iter_ms for every coalesced iteration
        from deeplearning4j_tpu.nn.listeners import iteration_wall_ns

        now = iteration_wall_ns(model)
        iter_ms = None if self._last_ns is None else (now - self._last_ns) / 1e6
        self._last_ns = now
        if iteration % self.frequency:
            return
        rec: Dict[str, Any] = {
            "session": self.session_id,
            "iteration": iteration,
            "epoch": epoch,
            "score": float(model.score_value),
            "time": time.time(),
        }
        if iter_ms is not None:
            rec["iter_ms"] = iter_ms
        if self.collect_histograms:
            params_stats = {}
            update_stats = {}
            cur = jax.tree_util.tree_map(np.asarray, model.params)
            # MLN: list of per-layer dicts; ComputationGraph: name → dict
            named = cur.items() if isinstance(cur, dict) else \
                ((f"layer{i}", p) for i, p in enumerate(cur))
            flat = {}
            for lname, p in named:
                if not isinstance(p, dict):
                    continue
                for k, v in p.items():
                    if isinstance(v, dict):
                        continue
                    flat[f"{lname}.{k}"] = np.asarray(v)
            for key, v in flat.items():
                params_stats[key] = _summary(v, bins=self.histogram_bins)
                if self._prev_params is not None and key in self._prev_params:
                    update_stats[key] = _summary(
                        v - self._prev_params[key], bins=self.histogram_bins)
            rec["params"] = params_stats
            if update_stats:
                rec["updates"] = update_stats
            self._prev_params = flat
        if self.collect_activations and \
                getattr(model, "last_features", None) is not None \
                and hasattr(model, "feed_forward"):
            lf = model.last_features
            # ComputationGraph stores its (possibly multi-) input tuple
            acts = model.feed_forward(*lf) if isinstance(lf, tuple) \
                else model.feed_forward(lf)
            bins = self.histogram_bins if self.collect_histograms else 0
            if isinstance(acts, dict):  # ComputationGraph: vertex name map
                named = acts.items()
            else:                       # MLN: list, [0] is the input itself
                named = ((f"layer{i}", a) for i, a in enumerate(acts[1:]))
            rec["activations"] = {
                str(k): _summary(np.asarray(a), bins=bins)
                for k, a in named}
        # recompile observability (docs/COMPILE_CACHE.md): trace/compile
        # counters ride every stats record so the UI/storage timeline shows
        # WHEN a shape-triggered recompile hit the training loop
        from deeplearning4j_tpu.util.compile_watcher import get_watcher

        rec["compile"] = get_watcher().counts()
        # telemetry group (docs/OBSERVABILITY.md): the registry's counters/
        # gauges ride along too, so one stats record correlates score,
        # compile state, pipeline health, and device memory at this step
        from deeplearning4j_tpu.util import telemetry as tele

        if tele.enabled():
            snap = tele.get_telemetry().snapshot()
            rec["telemetry"] = {"counters": snap["counters"],
                                "gauges": snap["gauges"]}
        # cost group (docs/OBSERVABILITY.md#cost-attribution--mfu): compact
        # totals/utilization of every published CostReport ride along, so a
        # stats record correlates score with FLOPs throughput and MFU; the
        # full per-layer table stays on the /costs route
        from deeplearning4j_tpu.util import cost_model

        cost = cost_model.cost_stats_group()
        if cost is not None:
            rec["cost"] = cost
        self.storage.put(rec)


class CrashReportingUtil:
    """CrashReportingUtil parity: state dump for post-mortems. Call from an
    except-block around fit() (the reference hooks OOM in the native
    allocator; PJRT raises RESOURCE_EXHAUSTED through jax instead)."""

    @staticmethod
    def write_crash_dump(model, path: str, exc: Optional[BaseException] = None):
        info: Dict[str, Any] = {
            "time": time.ctime(),
            "platform": platform.platform(),
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "exception": repr(exc) if exc else None,
            "iteration": getattr(model, "iteration", None),
            "epoch": getattr(model, "epoch", None),
            "score": float(getattr(model, "score_value", float("nan"))),
            "num_params": model.num_params() if hasattr(model, "num_params") else None,
        }
        # memory by param tree (host view of device buffers)
        sizes = {}
        for i, p in enumerate(getattr(model, "params", []) or []):
            for k, v in p.items():
                if hasattr(v, "nbytes"):
                    sizes[f"layer{i}.{k}"] = int(v.nbytes)
        info["param_bytes"] = sizes
        try:
            stats = jax.local_devices()[0].memory_stats()
            info["device_memory_stats"] = {
                k: int(v) for k, v in (stats or {}).items()}
        except Exception:
            info["device_memory_stats"] = None
        layers = getattr(model, "layers", None)
        if layers is not None:
            info["config"] = [type(l).__name__ for l in layers]
        # full model configuration JSON (the reference dumps the network
        # conf too — the crash report must reproduce the topology)
        conf = getattr(model, "conf", None)
        if conf is not None and hasattr(conf, "to_json"):
            try:
                info["config_json"] = json.loads(conf.to_json())
            except Exception:
                info["config_json"] = None
        # telemetry at the moment of death: every counter/gauge (incl. the
        # live/peak HBM gauges from the health monitors), histogram
        # summaries, health checks, and the last-50 trace events — what was
        # in flight when it crashed (docs/OBSERVABILITY.md)
        from deeplearning4j_tpu.util import telemetry as tele

        info["telemetry"] = tele.get_telemetry().snapshot(events_tail=50)
        info["hbm"] = [
            {"metric": name, **labels, "value": value}
            for name, labels, value in tele.device_memory_stats()]
        # serving flight recorder (docs/OBSERVABILITY.md#flight-recorder):
        # the last-N completed/shed/errored requests of every live router,
        # so a postmortem after a shed storm or drain has them in hand —
        # sys.modules-guarded like the /healthz serving section, a process
        # that never served pays nothing
        try:
            import sys as _sys

            _serving = _sys.modules.get("deeplearning4j_tpu.serving.router")
            snap = _serving.flight_snapshot(last=64) if _serving else {}
            if snap:
                info["serving_flight_recorder"] = snap
        except Exception:
            pass  # a broken recorder must never break the crash dump
        with open(path, "w") as f:
            json.dump(info, f, indent=2)
        return path
