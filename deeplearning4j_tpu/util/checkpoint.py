"""Sharded checkpointing: Orbax-backed save/restore of training state.

Reference parity: SURVEY.md §5.4 — the reference checkpoints via
ModelSerializer (zip of config JSON + flattened params + updater state;
implemented here in util/model_serializer.py) and CheckpointListener keep-N
rotation. The TPU-native counterpart is a SHARDED checkpoint: each host
writes its own param shards (no gather through one host), which is what
multi-host meshes need. This module wraps Orbax (baked into the image) with
the framework's state layout; the zip format remains for single-host
portability.

    ckpt = ShardedCheckpointer("/ckpts/run1", keep=3)
    ckpt.save(step, net)                  # params + opt state + iteration
    net2 = ...same conf...; ckpt.restore(net2)   # latest step
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


class ShardedCheckpointer:
    """Keep-N sharded checkpoints of a network's training state."""

    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep),
        )

    # ------------------------------------------------------------------ save
    def _state(self, model) -> dict:
        return {
            "params": model.params,
            "states": model.states,
            "opt_states": model.opt_states,
            "meta": {
                "iteration": np.asarray(model.iteration),
                "epoch": np.asarray(model.epoch),
            },
        }

    def save(self, step: int, model) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(self._state(model)))
        self._mgr.wait_until_finished()

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, model, step: Optional[int] = None):
        """Restore into an init()'d model of the same configuration (the
        abstract pytree comes from the model's current state, so shardings
        and dtypes round-trip)."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")

        def _abstract(x):
            # ShapeDtypeStruct leaves carry each param's sharding so device-
            # sharded state restores sharded (no gather through one host);
            # np.asarray here would materialize full host arrays and raise on
            # non-fully-addressable multi-host arrays.
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
            x = np.asarray(x)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        abstract = jax.tree_util.tree_map(_abstract, self._state(model))
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        model.params = restored["params"]
        model.states = restored["states"]
        model.opt_states = restored["opt_states"]
        model.iteration = int(restored["meta"]["iteration"])
        model.epoch = int(restored["meta"]["epoch"])
        return model

    def close(self):
        self._mgr.close()


class ShardedCheckpointListener:
    """CheckpointListener parity over the sharded format: save every
    ``frequency`` iterations, keep the last N."""

    def __init__(self, directory, frequency: int = 1000, keep: int = 3):
        """``directory``: a path, or an existing ShardedCheckpointer."""
        self.ckpt = (directory if isinstance(directory, ShardedCheckpointer)
                     else ShardedCheckpointer(directory, keep=keep))
        self.frequency = frequency

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.ckpt.save(iteration, model)


class FaultTolerantTrainer:
    """Checkpoint-restart training (SURVEY.md §5.3: the reference's failure
    story is Spark partition retry + CrashReportingUtil; the TPU-native story
    is restore-from-sharded-checkpoint and resume — slice preemptions and
    device OOMs surface as RuntimeError/XlaRuntimeError through jax).

        trainer = FaultTolerantTrainer(net, "/ckpts/run1",
                                       checkpoint_every=500, max_restarts=3)
        trainer.fit(iterator, epochs=10)
    """

    def __init__(self, model, directory: str, checkpoint_every: int = 1000,
                 keep: int = 3, max_restarts: int = 3,
                 crash_dump_path: Optional[str] = None):
        self.model = model
        self.ckpt = ShardedCheckpointer(directory, keep=keep)
        self.listener = ShardedCheckpointListener(self.ckpt,
                                                  frequency=checkpoint_every)
        self.max_restarts = max_restarts
        self.crash_dump_path = crash_dump_path

    def fit(self, iterator, epochs: int = 1):
        from deeplearning4j_tpu.util.stats import CrashReportingUtil

        if self.listener not in self.model.listeners:
            self.model.listeners.append(self.listener)
        restarts = 0
        try:
            while True:
                try:
                    start_epoch = self.model.epoch
                    self.model.fit(iterator, epochs=epochs - start_epoch)
                    return self.model
                except (RuntimeError, MemoryError, FloatingPointError) as e:
                    restarts += 1
                    if self.crash_dump_path:
                        CrashReportingUtil.write_crash_dump(
                            self.model, self.crash_dump_path, e)
                    if (restarts > self.max_restarts
                            or self.ckpt.latest_step() is None):
                        raise
                    self.ckpt.restore(self.model)  # roll back to last good step
        finally:
            if self.listener in self.model.listeners:
                self.model.listeners.remove(self.listener)
