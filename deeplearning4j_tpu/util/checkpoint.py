"""Sharded checkpointing: Orbax-backed save/restore of training state.

Reference parity: SURVEY.md §5.4 — the reference checkpoints via
ModelSerializer (zip of config JSON + flattened params + updater state;
implemented here in util/model_serializer.py) and CheckpointListener keep-N
rotation. The TPU-native counterpart is a SHARDED checkpoint: each host
writes its own param shards (no gather through one host). The zip format
remains for single-host portability.

Fault-tolerance contract (docs/FAULT_TOLERANCE.md):

- **Atomic commit** — every save writes to ``<dir>/.tmp-<step>`` and
  ``os.replace``-renames to ``<dir>/<step>`` only once the full tree (and
  the sidecar meta JSON) is on disk. A crash mid-save leaves a ``.tmp-*``
  orphan that listing ignores and the next save sweeps; it can never be
  mistaken for a restorable checkpoint.
- **Corruption-tolerant restore** — :meth:`restore_latest_good` walks the
  committed steps newest-first; a checkpoint that fails to load is skipped
  with a loud warning (``checkpoint.corrupt_skipped_total``), never a
  crash — the run resumes from the newest GOOD state.
- **Full resume state** — alongside params/opt state, the checkpoint
  carries the model's RNG key and iteration/epoch, plus caller-supplied
  sidecar metadata (the elastic runtime stores the batch-in-epoch cursor),
  so a resumed fit() is bit-identical to an uninterrupted one.
- **Retried I/O** — saves/restores run under a :class:`RetryPolicy`
  (util/faults.py): a flaky filesystem backs off and retries instead of
  killing the step loop on the first EIO.
- **Async save** — ``save(..., block=False)`` snapshots the state to host
  memory (fast) and commits in a background thread, so the step loop keeps
  the accelerator busy during checkpoint I/O; ``wait_until_finished()``
  joins (the elastic runtime drains it before exiting).

    ckpt = ShardedCheckpointer("/ckpts/run1", keep=3)
    ckpt.save(step, net)                  # params + opt state + iteration
    net2 = ...same conf...; ckpt.restore(net2)   # latest step
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.faults import RetryPolicy

_META_FILE = "elastic_meta.json"
_COMP_FILE = "grad_comp.npz"
_TMP_PREFIX = ".tmp-"


def _tree_spec(tree, arrays: list):
    """JSON-able spec of a nested dict/list/None pytree with array leaves
    (the shape of the gradient-compression state — residual tree +
    threshold). Leaves append to ``arrays`` and are referenced by index."""
    if tree is None:
        return {"t": "none"}
    if isinstance(tree, dict):
        return {"t": "dict",
                "items": [[k, _tree_spec(v, arrays)]
                          for k, v in tree.items()]}
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "items": [_tree_spec(v, arrays) for v in tree]}
    arrays.append(np.asarray(tree))
    return {"t": "arr", "i": len(arrays) - 1}


def _tree_unspec(spec, arrays):
    if spec["t"] == "none":
        return None
    if spec["t"] == "dict":
        return {k: _tree_unspec(v, arrays) for k, v in spec["items"]}
    if spec["t"] in ("list", "tuple"):
        items = [_tree_unspec(v, arrays) for v in spec["items"]]
        return items if spec["t"] == "list" else tuple(items)
    return arrays[spec["i"]]


def save_tree_npz(path: str, tree) -> None:
    """One-file (npz) serialization of a dict/list-structured pytree — the
    gradient-compression sidecar format (residual + threshold ride next to
    the orbax state inside the SAME atomic checkpoint commit)."""
    arrays: list = []
    spec = _tree_spec(tree, arrays)
    np.savez(path, __spec__=np.frombuffer(
        json.dumps(spec).encode(), dtype=np.uint8),
        **{f"a{i}": a for i, a in enumerate(arrays)})


def load_tree_npz(path: str):
    with np.load(path) as z:
        spec = json.loads(bytes(z["__spec__"].tobytes()).decode())
        arrays = {int(k[1:]): z[k] for k in z.files if k != "__spec__"}
    return _tree_unspec(spec, [arrays[i] for i in range(len(arrays))])

#: checkpoint I/O default: a couple of quick retries, bounded overall
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=2.0,
                        deadline=60.0)


class ShardedCheckpointer:
    """Keep-N sharded checkpoints of a network's training state."""

    def __init__(self, directory: str, keep: int = 3,
                 retry: Optional[RetryPolicy] = _IO_RETRY, log_fn=print):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self.retry = retry
        self.log = log_fn
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None
        self._lock = threading.Lock()
        #: steps THIS instance committed — gates the same-step fast path
        self._committed_steps: set = set()
        #: hooks fired AFTER a step's atomic rename lands (the commit→reload
        #: seam, docs/SERVING.md#resilience): ``hook(step)`` — runs on the
        #: committing thread (the background one for async saves), so hooks
        #: must read only what they capture, never the live model
        self._commit_hooks: list = []

    def add_commit_hook(self, hook) -> None:
        """Register ``hook(step)``, called after every successful commit
        (blocking and async alike). Hook failures are counted and logged,
        never raised — a broken observer must not fail a good checkpoint."""
        if hook not in self._commit_hooks:
            self._commit_hooks.append(hook)

    # ------------------------------------------------------------------ save
    def _state(self, model) -> dict:
        meta = {
            "iteration": np.asarray(model.iteration),
            "epoch": np.asarray(model.epoch),
        }
        if getattr(model, "_rng_key", None) is not None:
            # the key makes resume bit-identical: the restored fit() draws
            # the SAME dropout/shuffle streams the uninterrupted run would
            meta["rng_key"] = model._rng_key
        return {
            "params": model.params,
            "states": model.states,
            "opt_states": model.opt_states,
            "meta": meta,
        }

    @staticmethod
    def _host_snapshot(state: dict) -> dict:
        """Device -> host copy of the whole state tree. Decouples the saved
        bytes from the live buffers the NEXT train step will donate (a
        background save holding device references would read freed
        buffers)."""
        return jax.tree_util.tree_map(lambda a: np.asarray(jax.device_get(a)),
                                      state)

    def _commit(self, step: int, state: dict, extra_meta: Optional[dict],
                comp_state=None):
        """Write to .tmp-<step>, fsync-equivalent via orbax, then atomically
        rename into place and rotate keep-N. Runs under the retry policy."""
        import orbax.checkpoint as ocp

        # pid-qualified tmp: concurrent writers (two elastic members
        # misconfigured onto one directory, or a not-yet-reaped previous
        # incarnation) can never rmtree each other's in-flight write. The
        # supported layout is still ONE writer per directory — multi-host
        # pods give each process its own subdir (tests/_dist_worker.py) —
        # this is defense, not a coordination protocol.
        tmp = os.path.join(self.directory,
                           f"{_TMP_PREFIX}{step}-{os.getpid()}")
        final = os.path.join(self.directory, str(step))

        def write_meta(directory):
            # epoch rides the SIDECAR authoritatively: at an epoch boundary
            # two saves share one iteration but differ in epoch, and the
            # same-step fast path below refreshes only this file
            meta_tmp = os.path.join(directory, f"{_META_FILE}.tmp")
            with open(meta_tmp, "w") as f:
                json.dump({"step": step,
                           "epoch": int(state["meta"]["epoch"]),
                           **(extra_meta or {})}, f)
            os.replace(meta_tmp, os.path.join(directory, _META_FILE))

        def attempt():
            if os.path.isdir(final) and step in self._committed_steps:
                # same-step re-save by THIS run (drain right after a
                # cadence save): training state at a given iteration is
                # deterministic, so the committed arrays are already
                # right — refresh only the meta sidecar (atomic
                # single-file replace). NEVER delete a committed checkpoint
                # to rewrite identical bytes: the rmtree->rename window
                # would lose the step entirely on a crash between the two.
                write_meta(final)
                return
            if os.path.isdir(final):
                # a FOREIGN checkpoint at this step (directory reused by a
                # new run): keeping its arrays would silently persist the
                # OLD run's weights under the new run's save — move it
                # aside (swept once stale, like foreign tmps) and write
                # ours in full
                os.replace(final, os.path.join(
                    self.directory,
                    f".replaced-{step}-{os.getpid()}-{int(time.time())}"))
            if os.path.exists(tmp):
                shutil.rmtree(tmp)  # crashed/failed prior attempt
            ckptr = ocp.StandardCheckpointer()
            try:
                ckptr.save(tmp, state)
                if hasattr(ckptr, "wait_until_finished"):
                    ckptr.wait_until_finished()
            finally:
                close = getattr(ckptr, "close", None)
                if close:
                    close()
            if comp_state is not None:
                # gradient-compression sidecar (parallel/compression.py):
                # the error-feedback residual + adaptive threshold commit
                # ATOMICALLY with the params they pair with — a resumed
                # compressed fit continues the exact trajectory
                save_tree_npz(os.path.join(tmp, _COMP_FILE), comp_state)
            write_meta(tmp)
            os.replace(tmp, final)  # THE commit point

        with tm.span("elastic.checkpoint_commit", step=step):
            if self.retry is not None:
                self.retry.run(attempt, name="checkpoint_save",
                               retry_on=(OSError, ValueError))
            else:
                attempt()
        self._committed_steps.add(step)
        tm.counter("elastic.checkpoints_total")
        tm.gauge("elastic.last_checkpoint_step", step)
        for hook in list(self._commit_hooks):
            try:
                hook(step)
            except Exception as e:  # noqa: BLE001 — observer, not the save
                tm.counter("elastic.commit_hook_errors_total")
                if self.log:
                    self.log(f"WARNING: checkpoint commit hook failed at "
                             f"step {step}: {e!r}")
        self._rotate()

    def save(self, step: int, model, extra_meta: Optional[dict] = None,
             block: bool = True) -> None:
        """Checkpoint ``model`` at ``step``. ``extra_meta`` lands in a JSON
        sidecar (:meth:`load_meta`). ``block=False`` snapshots to host
        memory synchronously (cheap) and commits in a background thread —
        the caller's next train step overlaps the checkpoint I/O."""
        self.wait_until_finished()  # one in-flight save at a time
        state = self._host_snapshot(self._state(model))
        comp = getattr(model, "_grad_comp_state", None)
        if comp is not None:
            comp = self._host_snapshot(comp)
        if block:
            self._commit(step, state, extra_meta, comp_state=comp)
            return

        def run():
            try:
                self._commit(step, state, extra_meta, comp_state=comp)
            except BaseException as e:  # noqa: BLE001 — crosses the thread
                with self._lock:
                    self._pending_error = e
                tm.counter("elastic.checkpoint_errors_total")

        t = threading.Thread(target=run, name="dl4j-tpu-ckpt", daemon=True)
        self._pending = t
        t.start()

    def wait_until_finished(self) -> None:
        """Join any in-flight async save; re-raise its failure (once)."""
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None
        with self._lock:
            err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    def _rotate(self):
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, str(s)),
                          ignore_errors=True)
        # sweep crashed-run tmp orphans: _rotate runs inside _commit AFTER
        # its own tmp was renamed, and saves are serialized (save() joins
        # the pending one), so this process's .tmp-*-<pid> are dead weight.
        # Foreign-pid tmps are swept only once stale (an hour old) — if a
        # second writer IS racing on this directory despite the one-writer
        # contract, its live in-flight write survives.
        for name in os.listdir(self.directory):
            if not name.startswith((_TMP_PREFIX, ".replaced-")):
                continue
            path = os.path.join(self.directory, name)
            if (name.startswith(_TMP_PREFIX)
                    and name.endswith(f"-{os.getpid()}")):
                shutil.rmtree(path, ignore_errors=True)
                continue
            try:
                if time.time() - os.stat(path).st_mtime > 3600:
                    shutil.rmtree(path, ignore_errors=True)
            except OSError:
                pass

    # --------------------------------------------------------------- listing
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.isdigit() and os.path.isdir(
                    os.path.join(self.directory, name)):
                out.append(int(name))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def load_meta(self, step: int) -> Dict[str, Any]:
        """The JSON sidecar saved with ``extra_meta`` ({} when absent)."""
        path = os.path.join(self.directory, str(step), _META_FILE)
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    # --------------------------------------------------------------- restore
    def restore(self, model, step: Optional[int] = None):
        """Restore into an init()'d model of the same configuration (the
        abstract pytree comes from the model's current state, so shardings
        and dtypes round-trip)."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        path = os.path.join(self.directory, str(step))
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint for step {step} in "
                                    f"{self.directory}")

        def _abstract(x):
            # ShapeDtypeStruct leaves carry each param's sharding so device-
            # sharded state restores sharded (no gather through one host);
            # np.asarray here would materialize full host arrays and raise on
            # non-fully-addressable multi-host arrays.
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
            x = np.asarray(x)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        abstract = jax.tree_util.tree_map(_abstract, self._state(model))

        def attempt():
            ckptr = ocp.StandardCheckpointer()
            try:
                return ckptr.restore(path, abstract)
            finally:
                close = getattr(ckptr, "close", None)
                if close:
                    close()

        with tm.span("elastic.checkpoint_restore", step=step):
            if self.retry is not None:
                restored = self.retry.run(attempt, name="checkpoint_restore",
                                          retry_on=(OSError,))
            else:
                restored = attempt()
        model.params = restored["params"]
        model.states = restored["states"]
        model.opt_states = restored["opt_states"]
        model.iteration = int(restored["meta"]["iteration"])
        # the sidecar's epoch wins when present: a same-step re-save at an
        # epoch boundary refreshes only the sidecar (atomic file replace),
        # so the array-tree copy of the counter can be one epoch stale
        side = self.load_meta(step)
        model.epoch = int(side.get("epoch", restored["meta"]["epoch"]))
        if "rng_key" in restored["meta"] and hasattr(model, "_rng_key"):
            import jax.numpy as jnp

            model._rng_key = jnp.asarray(restored["meta"]["rng_key"])
        # gradient-compression sidecar: restore the error-feedback residual
        # + threshold alongside the params (ParallelWrapper re-adopts the
        # model-side tree on its next step — parallel/wrapper.py). A
        # checkpoint WITHOUT the sidecar resets any live compression state:
        # the restored params never saw that residual.
        comp_path = os.path.join(path, _COMP_FILE)
        if os.path.exists(comp_path):
            model._grad_comp_state = load_tree_npz(comp_path)
        elif getattr(model, "_grad_comp_state", None) is not None:
            model._grad_comp_state = None
        return model

    def restore_latest_good(self, model) -> Optional[int]:
        """Walk committed checkpoints newest-first; skip (warn + count) any
        that fail to load — a partial/corrupt newest checkpoint must not
        kill the resume. Returns the restored step, or None when no
        checkpoint exists / none loads."""
        for step in reversed(self.all_steps()):
            try:
                self.restore(model, step=step)
                return step
            except Exception as e:  # noqa: BLE001 — skip bad, keep walking
                tm.counter("checkpoint.corrupt_skipped_total")
                tm.instant("checkpoint.corrupt_skipped", step=step,
                           error=f"{type(e).__name__}: {e}"[:200])
                if self.log:
                    self.log(f"WARNING: checkpoint step {step} in "
                             f"{self.directory} failed to load "
                             f"({type(e).__name__}: {e}); trying older")
                # quarantine the corpse (rename, NEVER delete): it must not
                # shadow a future save at the same step (same-step re-saves
                # keep existing arrays) nor be re-probed on every resume —
                # but the failure may be a config mismatch or a transient
                # FS error, not corruption, and erasing possibly-good user
                # checkpoints on a load error is how runs become
                # unrecoverable. The renamed dir is invisible to listing
                # (non-digit name) and left for forensics.
                src = os.path.join(self.directory, str(step))
                dst = os.path.join(self.directory,
                                   f".unloadable-{step}-{os.getpid()}")
                try:
                    if not os.path.exists(dst):
                        os.replace(src, dst)
                except OSError:
                    pass  # can't even rename: leave it; listing still works
        return None

    def close(self):
        self.wait_until_finished()


class ShardedCheckpointListener:
    """CheckpointListener parity over the sharded format: save every
    ``frequency`` iterations, keep the last N."""

    def __init__(self, directory, frequency: int = 1000, keep: int = 3):
        """``directory``: a path, or an existing ShardedCheckpointer."""
        self.ckpt = (directory if isinstance(directory, ShardedCheckpointer)
                     else ShardedCheckpointer(directory, keep=keep))
        self.frequency = frequency

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.ckpt.save(iteration, model)


class FaultTolerantTrainer:
    """Checkpoint-restart training (SURVEY.md §5.3: the reference's failure
    story is Spark partition retry + CrashReportingUtil; the TPU-native story
    is restore-from-sharded-checkpoint and resume — slice preemptions and
    device OOMs surface as RuntimeError/XlaRuntimeError through jax).

    The supervised loop with membership/regroup/drain on top of this lives
    in parallel/elastic.py (ElasticTrainer).

        trainer = FaultTolerantTrainer(net, "/ckpts/run1",
                                       checkpoint_every=500, max_restarts=3)
        trainer.fit(iterator, epochs=10)
    """

    def __init__(self, model, directory: str, checkpoint_every: int = 1000,
                 keep: int = 3, max_restarts: int = 3,
                 crash_dump_path: Optional[str] = None):
        self.model = model
        self.ckpt = ShardedCheckpointer(directory, keep=keep)
        self.listener = ShardedCheckpointListener(self.ckpt,
                                                  frequency=checkpoint_every)
        self.max_restarts = max_restarts
        self.crash_dump_path = crash_dump_path

    def fit(self, iterator, epochs: int = 1):
        from deeplearning4j_tpu.util.stats import CrashReportingUtil

        if self.listener not in self.model.listeners:
            self.model.listeners.append(self.listener)
        restarts = 0
        try:
            while True:
                try:
                    start_epoch = self.model.epoch
                    self.model.fit(iterator, epochs=epochs - start_epoch)
                    return self.model
                except (RuntimeError, MemoryError, FloatingPointError) as e:
                    restarts += 1
                    if self.crash_dump_path:
                        CrashReportingUtil.write_crash_dump(
                            self.model, self.crash_dump_path, e)
                    if (restarts > self.max_restarts
                            or self.ckpt.latest_step() is None):
                        raise
                    # roll back to the newest checkpoint that LOADS — the
                    # crash may have corrupted the newest one mid-write
                    if self.ckpt.restore_latest_good(self.model) is None:
                        raise
        finally:
            if self.listener in self.model.listeners:
                self.model.listeners.remove(self.listener)
