"""Model checkpointing — full-fidelity save/restore.

Reference parity: org/deeplearning4j/util/ModelSerializer.java — a zip of
``configuration.json`` (Jackson config), ``coefficients.bin`` (flattened
params), ``updaterState.bin`` (optimizer state), optional normalizer — such
that ``restoreMultiLayerNetwork(file, true).fit(...)`` resumes training
bit-for-bit (SURVEY.md §5.4; path-cite, mount empty this round).

TPU-native shape: params/opt-state are device pytrees, not one flattened
off-heap buffer, so the archive stores each leaf as an .npy member inside the
zip (numpy savez container) in deterministic tree-flatten order, plus a
structure fingerprint to catch config/weight mismatches. The RNG key,
iteration and epoch counters ride along so dropout streams and LR schedules
resume exactly. Normalizers (DataNormalization) serialize alongside, as in
the reference's ``addNormalizerToModel``.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any, Optional

import jax
import numpy as np

_CONFIG = "configuration.json"
_COEFF = "coefficients.npz"
_STATE = "state.npz"
_UPDATER = "updaterState.npz"
_META = "meta.json"
_NORMALIZER = "normalizer.json"
_SCALES = "quantScales.npz"  # int8 archives: per-channel scales


def _leaves(tree) -> list:
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _fingerprint(tree) -> str:
    return str(jax.tree_util.tree_structure(tree))


def _savez(leaves) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, *leaves)
    return buf.getvalue()


def _loadz(data: bytes) -> list:
    z = np.load(io.BytesIO(data))
    return [z[f"arr_{i}"] for i in range(len(z.files))]


def _refill(tree, leaves):
    """Pour saved leaves back into the live tree's structure (device_put on
    current default device; shardings are re-established by the caller)."""
    treedef = jax.tree_util.tree_structure(tree)
    old = jax.tree_util.tree_leaves(tree)
    if len(old) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} arrays, model needs {len(old)} "
            "(configuration mismatch)"
        )
    cast = []
    for i, (l, o) in enumerate(zip(leaves, old)):
        l = np.asarray(l)
        if hasattr(o, "shape") and tuple(l.shape) != tuple(o.shape):
            raise ValueError(
                f"checkpoint array {i} has shape {tuple(l.shape)}, model "
                f"expects {tuple(o.shape)} (configuration mismatch)"
            )
        cast.append(l.astype(o.dtype) if hasattr(o, "dtype") else l)
    return jax.tree_util.tree_unflatten(treedef, cast)


class ModelSerializer:
    """Static save/restore API (ModelSerializer.java parity)."""

    # ------------------------------------------------------------------ save
    @staticmethod
    def write_model(model, path: str, save_updater: bool = True,
                    normalizer=None, quantize: str = None) -> None:
        """``quantize="int8"`` writes a weight-only int8 SERVING archive
        (docs/SERVING.md#paged-kv--speculative-decode): weight matrices/
        embedding tables as int8 + per-channel fp32 scales (archive bytes
        ~4× below fp32 — the dominant .npz members shrink 4×), updater
        state never included (a quantized archive is a deployment
        artifact, not a training checkpoint). ``restore_*`` dequantizes
        back to an fp32 net AND stashes the stored int8 leaves on
        ``net._int8_archive`` so ``ModelRouter.load(quantize="int8")``
        serves the archive's exact quantization — bit-identical round
        trip (serving/quantize.py)."""
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(model, MultiLayerNetwork):
            mtype = "MultiLayerNetwork"
        elif isinstance(model, ComputationGraph):
            mtype = "ComputationGraph"
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r}")

        meta = {
            "type": mtype,
            "iteration": int(model.iteration),
            "epoch": int(model.epoch),
            "rng_key": np.asarray(model._rng_key).tolist(),
            "params_structure": _fingerprint(model.params),
            "has_updater_state": bool(save_updater) and quantize is None,
            "format_version": 1,
        }
        if quantize == "int8":
            from deeplearning4j_tpu.serving.quantize import QuantizedParams

            qp = QuantizedParams.from_params(model.params)
            meta["quantize"] = "int8"
            meta["fp32_bytes"] = qp.fp32_bytes()
            # None scales (pass-through leaves) ride as size-0 arrays —
            # npz members must be arrays; restore maps size-0 back to None
            scales = [s if s is not None else np.zeros(0, np.float32)
                      for s in qp.scales]
            entries = [(_CONFIG, model.conf.to_json()),
                       (_COEFF, _savez(qp.qleaves)),
                       (_SCALES, _savez(scales)),
                       (_STATE, _savez(_leaves(model.states)))]
        else:
            entries = [(_CONFIG, model.conf.to_json()),
                       (_COEFF, _savez(_leaves(model.params))),
                       (_STATE, _savez(_leaves(model.states)))]
            if save_updater:
                entries.append((_UPDATER, _savez(_leaves(model.opt_states))))
        entries.append((_META, json.dumps(meta)))
        if normalizer is not None:
            entries.append((_NORMALIZER, json.dumps(normalizer.to_dict())))
        ModelSerializer._write_zip(path, entries)

    @staticmethod
    def _write_zip(path: str, entries) -> None:
        """Atomic publish: write the whole zip to a tmp sibling, then
        os.replace into place — a reader (the serving watch poller,
        docs/SERVING.md#resilience) can never observe a torn archive, and
        a crash mid-write leaves only the tmp corpse."""
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
                for name, data in entries:
                    zf.writestr(name, data)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # ------------------------------------------------------------- snapshot
    @staticmethod
    def snapshot(model) -> dict:
        """Capture everything a no-updater ``write_model`` reads as HOST
        arrays, on the caller's thread. The device→host copy is mandatory,
        not an optimization to skip: the train step donates the param
        buffers (nn/multilayer.py ``donate_argnums``), so a background
        writer holding device refs would read freed buffers — the same
        reason ``ShardedCheckpointer._host_snapshot`` exists. The
        still-expensive DEFLATE + write happen later on the writer thread
        via :meth:`write_snapshot` — the elastic publish seam
        (docs/SERVING.md#resilience) without stalling the step loop on
        compression."""
        import jax

        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(model, MultiLayerNetwork):
            mtype = "MultiLayerNetwork"
        elif isinstance(model, ComputationGraph):
            mtype = "ComputationGraph"
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        host = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda a: np.asarray(jax.device_get(a)), tree)
        return {
            "conf_json": model.conf.to_json(),
            "params": host(model.params),
            "states": host(model.states),
            "meta": {
                "type": mtype,
                "iteration": int(model.iteration),
                "epoch": int(model.epoch),
                "rng_key": np.asarray(model._rng_key).tolist(),
                "params_structure": _fingerprint(model.params),
                "has_updater_state": False,
                "format_version": 1,
            },
        }

    @staticmethod
    def write_snapshot(snap: dict, path: str) -> None:
        """Serialize a :meth:`snapshot` capture to ``path`` (atomic). Safe
        on any thread — the snapshot owns immutable tree refs."""
        ModelSerializer._write_zip(path, [
            (_CONFIG, snap["conf_json"]),
            (_COEFF, _savez(_leaves(snap["params"]))),
            (_STATE, _savez(_leaves(snap["states"]))),
            (_META, json.dumps(snap["meta"])),
        ])

    # --------------------------------------------------------------- restore
    @staticmethod
    def _restore(path: str, expect_type: Optional[str], load_updater: bool):
        from deeplearning4j_tpu.nn.computation_graph import (
            ComputationGraph,
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read(_META))
            if expect_type and meta["type"] != expect_type:
                raise ValueError(
                    f"archive holds a {meta['type']}, expected {expect_type}"
                )
            cfg_json = zf.read(_CONFIG).decode()
            if meta["type"] == "MultiLayerNetwork":
                net = MultiLayerNetwork(
                    MultiLayerConfiguration.from_json(cfg_json)
                ).init()
            else:
                net = ComputationGraph(
                    ComputationGraphConfiguration.from_json(cfg_json)
                ).init()
            fp = _fingerprint(net.params)
            if meta.get("params_structure") and meta["params_structure"] != fp:
                raise ValueError(
                    "checkpoint param structure does not match the model built "
                    "from its configuration (corrupt or hand-edited archive)"
                )
            if meta.get("quantize") == "int8":
                # int8 serving archive: dequantize back to an fp32 net
                # (the generic restore contract holds everywhere), and
                # stash the STORED quantization so a quantize="int8"
                # serving load adopts it verbatim — no re-quantization
                # drift, bit-identical round trip (serving/quantize.py)
                qleaves = _loadz(zf.read(_COEFF))
                scales = [None if s.size == 0 else s
                          for s in _loadz(zf.read(_SCALES))]
                if len(qleaves) != len(scales):
                    raise ValueError(
                        "int8 archive scale count does not match its "
                        "coefficient count (corrupt archive)")
                from deeplearning4j_tpu.ops.compression import dequantize_np

                deq = [q if s is None else dequantize_np(q, s)
                       for q, s in zip(qleaves, scales)]
                net.params = _refill(net.params, deq)
                net._int8_archive = (
                    jax.tree_util.tree_structure(net.params),
                    qleaves, scales)
            else:
                net.params = _refill(net.params, _loadz(zf.read(_COEFF)))
            net.states = _refill(net.states, _loadz(zf.read(_STATE)))
            if load_updater and meta.get("has_updater_state") and _UPDATER in zf.namelist():
                net.opt_states = _refill(net.opt_states, _loadz(zf.read(_UPDATER)))
            elif getattr(net, "_fused", None) is not None:
                # fused engine invariant (nn/updaters.py): the resident
                # master buffers were built from init()'s random params —
                # resync them to the LOADED params, or the first fit() step
                # would snap the trained weights back to random init
                net.opt_states = net._fused.resync_masters(
                    net.params, net.opt_states)
            net.iteration = meta["iteration"]
            net.epoch = meta["epoch"]
            net._rng_key = jax.numpy.asarray(
                np.array(meta["rng_key"], dtype=np.uint32)
            )
        return net

    @staticmethod
    def peek_meta(path: str) -> dict:
        """Archive metadata (type, iteration, epoch, format_version) WITHOUT
        building the model — the serving router's registry/listing path
        (serving/router.py): a model catalog can be enumerated without
        paying a restore per entry."""
        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read(_META))
        return {k: meta[k] for k in
                ("type", "iteration", "epoch", "format_version",
                 "quantize", "fp32_bytes")
                if k in meta}

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, "MultiLayerNetwork", load_updater)

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, "ComputationGraph", load_updater)

    @staticmethod
    def restore_model(path: str, load_updater: bool = True):
        return ModelSerializer._restore(path, None, load_updater)

    # ------------------------------------------------------------ normalizer
    @staticmethod
    def restore_normalizer_from_file(path: str):
        from deeplearning4j_tpu.data.normalizers import normalizer_from_dict

        with zipfile.ZipFile(path, "r") as zf:
            if _NORMALIZER not in zf.namelist():
                return None
            return normalizer_from_dict(json.loads(zf.read(_NORMALIZER)))

    @staticmethod
    def add_normalizer_to_model(path: str, normalizer) -> None:
        """addNormalizerToModel parity — attach post hoc to an archive."""
        with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(_NORMALIZER, json.dumps(normalizer.to_dict()))
