"""Cross-cutting utilities (reference: org/deeplearning4j/util/** and
nd4j-common — SURVEY.md §2.2 J20)."""

from deeplearning4j_tpu.util.model_serializer import ModelSerializer

__all__ = ["ModelSerializer"]
