"""Cross-cutting utilities (reference: org/deeplearning4j/util/** and
nd4j-common — SURVEY.md §2.2 J20)."""

from deeplearning4j_tpu.util.checkpoint import (
    FaultTolerantTrainer,
    ShardedCheckpointer,
    ShardedCheckpointListener,
)
from deeplearning4j_tpu.util import xla_tuning
from deeplearning4j_tpu.util.aot_store import AotStore
from deeplearning4j_tpu.util.compile_cache import (
    cache_entries,
    clear_persistent_cache,
    disable_persistent_cache,
    enable_persistent_cache,
)
from deeplearning4j_tpu.util.compile_watcher import (
    CompileScope,
    CompileWatcher,
    get_watcher,
    note_trace,
)
from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util.packed import PackedTrainer, StatePacker
from deeplearning4j_tpu.util.profiler import (
    NaNPanicError,
    OpProfiler,
    ProfilerConfig,
    StepTimer,
    check_numerics,
    device_trace,
)
from deeplearning4j_tpu.util.stats import (
    CrashReportingUtil,
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsListener,
    to_csv,
)
from deeplearning4j_tpu.util import cost_model
from deeplearning4j_tpu.util import telemetry
from deeplearning4j_tpu.util.cost_model import CostReport, CostRow
from deeplearning4j_tpu.util.faults import (
    FaultInjector,
    RetryExhaustedError,
    RetryPolicy,
    get_injector,
)
from deeplearning4j_tpu.util.health import (
    RollbackSignal,
    TrainingHealthMonitor,
)
from deeplearning4j_tpu.util.telemetry import Telemetry, get_telemetry

__all__ = [
    "ModelSerializer", "ShardedCheckpointer", "ShardedCheckpointListener",
    "FaultTolerantTrainer", "OpProfiler", "ProfilerConfig", "StepTimer",
    "NaNPanicError", "check_numerics", "device_trace", "CrashReportingUtil",
    "FileStatsStorage", "InMemoryStatsStorage", "StatsListener", "to_csv",
    "PackedTrainer", "StatePacker", "xla_tuning",
    "CompileWatcher", "CompileScope", "get_watcher", "note_trace",
    "enable_persistent_cache", "disable_persistent_cache",
    "clear_persistent_cache", "cache_entries", "AotStore",
    "telemetry", "Telemetry", "get_telemetry", "TrainingHealthMonitor",
    "cost_model", "CostReport", "CostRow",
    "RetryPolicy", "RetryExhaustedError", "FaultInjector", "get_injector",
    "RollbackSignal",
]
