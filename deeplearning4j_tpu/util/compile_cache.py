"""Persistent (on-disk) XLA compilation cache wiring.

A fresh process pays a full trace+compile for every jitted program even when
an identical binary was built seconds earlier by the previous run — the
cold-start cost that dominates serving restart tail latency (ROADMAP north
star). JAX ships a content-addressed on-disk executable cache
(``jax_compilation_cache_dir``); this module wires it with
deployment-friendly thresholds and one env knob:

- ``DL4J_TPU_COMPILE_CACHE=<dir>`` — enable at import via config.py
  (Environment), no code change needed (the reference's
  ``cudnnAlgoMode``/workspace-reuse analogue, but across PROCESSES).
- :func:`enable_persistent_cache` — programmatic form; returns the dir.

Cache keys include the XLA/jaxlib version, backend, and the full HLO — a
jaxlib upgrade or code change misses cleanly (stale entries are harmless;
``clear_persistent_cache`` prunes). Thresholds default to cache-everything
(min compile time 0s, no min entry size): on the CPU host even small
programs are worth a disk hit, and on the real chip large programs dominate
anyway. See docs/COMPILE_CACHE.md for layout/invalidation caveats.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "deeplearning4j_tpu", "xla_cache")

_enabled_dir: Optional[str] = None


def enable_persistent_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: float = 0.0,
    min_entry_size_bytes: int = -1,
) -> str:
    """Point ``jax_compilation_cache_dir`` at ``cache_dir`` (created if
    missing) so every XLA compile is persisted and a later process
    deserializes instead of recompiling. Idempotent; returns the dir."""
    global _enabled_dir
    import jax

    cache_dir = os.path.abspath(
        cache_dir or os.environ.get("DL4J_TPU_COMPILE_CACHE") or _DEFAULT_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    from deeplearning4j_tpu.util import telemetry as tm

    tm.counter("compile_cache.enables_total")
    # cache-everything thresholds: the jax defaults (1s / small-entry skip)
    # are tuned for TPU pods where only big programs matter; our cold-start
    # metric counts EVERY program in the step dispatch chain
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs)
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes", min_entry_size_bytes)
    _reset_jax_cache()
    _enabled_dir = cache_dir
    return cache_dir


def _reset_jax_cache() -> None:
    """Re-initialize jax's cache object: the config updates alone do NOT
    take effect once the first compile has latched a no-dir cache (enabling
    mid-process — the Environment applies env config lazily)."""
    try:
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass  # older/newer jax: the config applies at first compile instead


def disable_persistent_cache() -> None:
    global _enabled_dir
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache()
    _enabled_dir = None


def cache_dir() -> Optional[str]:
    """The active cache dir, or None when the persistent cache is off."""
    return _enabled_dir


def cache_entries(path: Optional[str] = None) -> int:
    """Number of persisted executables in the cache dir (0 if absent)."""
    path = path or _enabled_dir
    if not path or not os.path.isdir(path):
        return 0
    return sum(1 for f in os.listdir(path) if f.endswith("-cache"))


def clear_persistent_cache(path: Optional[str] = None) -> None:
    """Remove every entry under the cache dir (the dir itself stays)."""
    path = path or _enabled_dir
    if not path or not os.path.isdir(path):
        return
    for name in os.listdir(path):
        full = os.path.join(path, name)
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        else:
            try:
                os.remove(full)
            except OSError:
                pass
