"""Fusion-boundary engineering: selective rematerialization + XLA tuning.

Why this module exists (BASELINE.md round-5): the flagship ResNet-50 step's
device floor decomposes into ≈35.5 ms irreducible conv compute + ≈35.2 ms
bandwidth-floor non-conv work + **≈36 ms fusion-context cost** — convs inside
the fused train step run at roughly half their isolated efficiency. Whole-loss
remat was measured and REJECTED (+32%, r5): recomputing the convs costs full
price. The open lever is *finer-grained* control of what XLA keeps live
across the forward/backward boundary and where fusion regions end:

- **Selective remat** (`jax.checkpoint` + `checkpoint_policies`): per-stage
  policies that SAVE the expensive conv/dot outputs and recompute only the
  cheap elementwise/BN epilogue in the backward pass. The conv ops in
  ``ops/nn.py`` tag their outputs with ``checkpoint_name(..., 'conv_out')``
  (dense matmuls tag ``'dot_out'``) so name-based policies can target them.
- **Optimization barriers** (`lax.optimization_barrier`) at residual-stage
  boundaries: forbids XLA from fusing across stages, bounding the live-range
  and memory pressure each fusion region sees.
- **XLA flag candidates** for the sweep harness (`benchmarks/fusion_sweep.py`):
  process-global scheduling/fusion knobs, validated per-build in a subprocess
  (unknown flags abort XLA, so candidates never run in-process).

This is the schedule/fusion search space TVM explores automatically
(PAPERS.md: arXiv 1802.04799) applied to the path the reference delegated to
cuDNN's hand-tuned primitives (arXiv 1410.0759).

Usage: ``NeuralNetConfiguration.builder().remat_policy('save_conv')`` plus
``stage_boundary()`` markers (the zoo ResNet-50 marks its residual stages);
the config JSON round-trips. ``DL4J_TPU_REMAT_POLICY`` sets the default.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
from jax import lax
from jax.ad_checkpoint import checkpoint_name

# Names used by ops/nn.py to tag rematerialization-relevant outputs.
CONV_OUT = "conv_out"
DOT_OUT = "dot_out"

_cp = jax.checkpoint_policies

# name -> factory returning a jax checkpoint policy, or None for "recompute
# everything inside the stage" (jax.checkpoint's default behaviour).
_POLICIES: Dict[str, Optional[Callable[[], Any]]] = {
    # per-stage full remat: save only the stage-boundary activations
    "full": None,
    # save conv outputs, recompute the cheap BN/elementwise epilogue
    "save_conv": lambda: _cp.save_only_these_names(CONV_OUT),
    # save conv AND dense-matmul outputs
    "save_conv_dots": lambda: _cp.save_from_both_policies(
        _cp.save_only_these_names(CONV_OUT, DOT_OUT),
        _cp.dots_with_no_batch_dims_saveable,
    ),
    # save every non-batched dot (transformer-style policy; convs recompute)
    "save_dots": lambda: _cp.dots_with_no_batch_dims_saveable,
    # save everything: remat-free, but the checkpoint stages still scope
    # XLA's fusion regions (A/B candidate for boundary effects alone)
    "save_all": lambda: _cp.everything_saveable,
}


def policy_names() -> List[str]:
    """Registered policy names ('none' disables wrapping)."""
    return ["none"] + sorted(_POLICIES)


def register_policy(name: str, factory: Optional[Callable[[], Any]]):
    """Register a custom policy (factory -> jax checkpoint policy, or None
    for full per-stage remat)."""
    _POLICIES[name] = factory
    return factory


def resolve_policy(name: Optional[str]) -> Tuple[bool, Optional[Any]]:
    """(wrap_stages, checkpoint_policy) for a configured policy name.

    ``None``/'none' -> (False, None): stages run unwrapped.
    'full'          -> (True, None): jax.checkpoint default (recompute all).
    otherwise       -> (True, policy) from the registry.
    """
    if name is None or name == "none":
        return False, None
    try:
        factory = _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown remat policy {name!r}; known: {policy_names()}"
        ) from None
    return True, (factory() if factory is not None else None)


def checkpoint_stage(fn: Callable, policy_name: Optional[str]) -> Callable:
    """Wrap one stage function in jax.checkpoint per the named policy
    (identity for 'none')."""
    wrap, policy = resolve_policy(policy_name)
    if not wrap:
        return fn
    return jax.checkpoint(fn, policy=policy)


def tag(x, name: str):
    """Tag an intermediate for name-based checkpoint policies. Transparent
    (identity) outside a jax.checkpoint region."""
    return checkpoint_name(x, name)


@jax.custom_vjp
def barrier(tree):
    """Fusion fence: forbids XLA from fusing/scheduling across this point.
    Accepts any pytree of arrays and returns it unchanged in value.
    Differentiable (``lax.optimization_barrier`` has no autodiff rule): the
    cotangents pass through a barrier too, fencing the backward stage
    boundaries symmetrically with the forward ones."""
    return lax.optimization_barrier(tree)


def _barrier_fwd(tree):
    return lax.optimization_barrier(tree), None


def _barrier_bwd(_, ct):
    return (lax.optimization_barrier(ct),)


barrier.defvjp(_barrier_fwd, _barrier_bwd)


# --------------------------------------------------------------------------
# XLA flag-sweep candidates (benchmarks/fusion_sweep.py)
# --------------------------------------------------------------------------
# Each candidate is (name, flag-string appended to XLA_FLAGS). Flags are
# process-global and unknown flags ABORT XLA at client init, so the harness
# applies them only in a fresh subprocess and reports per-build validity
# instead of assuming it. TPU-prefixed flags are expected to be rejected on
# the CPU backend — that rejection is itself recorded in the sweep table.
XLA_FLAG_CANDIDATES: List[Tuple[str, str]] = [
    ("flags:opt_level_2", "--xla_backend_optimization_level=2"),
    ("flags:no_xla_remat", "--xla_disable_hlo_passes=rematerialization"),
    ("flags:tpu_vmem_64M", "--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("flags:tpu_no_latency_sched",
     "--xla_tpu_enable_latency_hiding_scheduler=false"),
]
