"""UIServer: browser dashboard over a StatsStorage.

Reference parity: deeplearning4j-ui (VertxUIServer + training charts —
SURVEY.md §2.2 J19) — path-cite, mount empty this round. The reference runs
a Vert.x web app; here a stdlib http.server thread serves the same content
model: score/time charts from the attached StatsStorage, rendered with an
inline-SVG page (no JS dependencies, no egress).

    from deeplearning4j_tpu.util import InMemoryStatsStorage, StatsListener
    from deeplearning4j_tpu.util.ui_server import UIServer

    storage = InMemoryStatsStorage()
    net.listeners.append(StatsListener(storage))
    ui = UIServer.get_instance()
    ui.attach(storage)              # http://localhost:9000/train
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional


class UIServer:
    """UIServer.getInstance()/attach(storage) parity."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storages: List = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._sys_history: List = []  # (timestamp, process RSS MB) samples

    @classmethod
    def get_instance(cls, port: "Optional[int]" = None) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(9000 if port is None else port)
        elif port is not None and port != cls._instance.port:
            raise ValueError(
                f"UIServer already running on port {cls._instance.port}; "
                f"stop() it before requesting port {port}")
        return cls._instance

    def attach(self, storage) -> "UIServer":
        self.storages.append(storage)
        if self._httpd is None:
            self._start()
        return self

    def detach(self, storage):
        self.storages.remove(storage)

    def _records(self, session: "Optional[str]" = None):
        out = []
        for s in self.storages:
            out.extend(r for r in s.records
                       if session is None or r.get("session") == session)
        return sorted(out, key=lambda r: r.get("iteration", 0))

    def _sessions(self) -> List[str]:
        """All session ids across attached storages (StatsStorage
        listSessionIDs parity — the reference UI's session browser)."""
        ids = set()
        for s in self.storages:
            for r in s.records:
                if "session" in r:
                    ids.add(r["session"])
        return sorted(ids)

    def _newest_session(self) -> "Optional[str]":
        """Session of the most recently inserted record (storage lists are
        append-ordered) — 'newest' by actual arrival, not id spelling."""
        for s in reversed(self.storages):
            for r in reversed(s.records):
                if "session" in r:
                    return r["session"]
        return None

    def _start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, body: bytes, ctype: str, status: int = 200):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, unquote, urlparse

                u = urlparse(self.path)
                q = parse_qs(u.query)
                session = q.get("session", [None])[0]
                if u.path == "/metrics":
                    # Prometheus exposition (docs/OBSERVABILITY.md): the
                    # process telemetry registry + scrape-time collectors
                    # (compile counters, HBM stats, cache entries)
                    self._send(server._metrics_text().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif u.path == "/healthz":
                    body, ok = server._healthz()
                    self._send(body.encode(), "application/json",
                               status=200 if ok else 503)
                elif u.path == "/costs":
                    # cost attribution (docs/OBSERVABILITY.md): every
                    # published CostReport — per-layer FLOPs/bytes/time
                    # table, totals, achieved FLOP/s, MFU — as JSON
                    self._send(server._costs_json().encode(),
                               "application/json")
                elif u.path == "/slo":
                    # SLO evaluation (docs/OBSERVABILITY.md#request-
                    # tracing--slos): every declared objective's current
                    # compliance, per-window burn rates, budget remaining
                    self._send(server._slo_json().encode(),
                               "application/json")
                elif u.path == "/train/sessions":
                    self._send(json.dumps(server._sessions()).encode(),
                               "application/json")
                elif u.path.startswith("/train/data"):
                    self._send(
                        json.dumps(server._records(session)).encode(),
                        "application/json")
                elif u.path.startswith("/train/session/"):
                    sid = unquote(u.path[len("/train/session/"):].rstrip("/"))
                    self._send(server._render(sid).encode(), "text/html")
                elif u.path.startswith("/train/histograms"):
                    self._send(server._render_histograms(session).encode(),
                               "text/html")
                elif u.path.startswith("/train/system"):
                    self._send(server._render_system().encode(),
                               "text/html")
                elif u.path in ("/", "/train", "/train/"):
                    self._send(server._render(session).encode(), "text/html")
                else:
                    self.send_response(404)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolves port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if UIServer._instance is self:
            UIServer._instance = None

    # ------------------------------------------------- telemetry endpoints
    @staticmethod
    def _metrics_text() -> str:
        """Prometheus text for /metrics: install the default collectors on
        first scrape so compile/HBM/cache gauges appear without any caller
        wiring (docs/OBSERVABILITY.md lists the metric names)."""
        from deeplearning4j_tpu.util import telemetry as tm

        return tm.install_default_collectors().prometheus_text()

    @staticmethod
    def _costs_json() -> str:
        """JSON body for /costs: the reports published by
        ``net.cost_report()`` (util/cost_model.py), keyed by model name.
        Empty object until a report has been computed — the route never
        errors, so dashboards can poll it unconditionally."""
        from deeplearning4j_tpu.util import cost_model

        return json.dumps({"reports": cost_model.published_reports()})

    @staticmethod
    def _slo_json() -> str:
        """JSON body for /slo: the SLO engine's evaluation (util/slo.py).
        Lazy import — hitting the route is the opt-in; an empty objectives
        list comes back until something is declared, so dashboards can
        poll unconditionally."""
        from deeplearning4j_tpu.util import slo

        doc = slo.current_status()
        return json.dumps(doc if doc else {"objectives": []})

    @staticmethod
    def _healthz() -> "tuple[str, bool]":
        """(JSON body, healthy?) for /healthz: aggregates every health
        check published by util/health.py monitors, plus device liveness
        (PJRT still answers), plus the elastic runtime's membership section
        (world/rank/alive members, rollback/resume/drain history —
        docs/FAULT_TOLERANCE.md). Unhealthy serves HTTP 503 so a k8s probe
        or LB drains the task without parsing the body."""
        from deeplearning4j_tpu.util import telemetry as tm

        slo_status = {}
        try:
            import sys

            # SLO section (docs/OBSERVABILITY.md#request-tracing--slos):
            # evaluated BEFORE the health report is read, so a budget that
            # exhausted since the last probe flips THIS response to 503 —
            # same sys.modules guard as elastic/serving/tuning below
            _slo = sys.modules.get("deeplearning4j_tpu.util.slo")
            slo_status = _slo.current_status() if _slo else {}
        except Exception:
            pass  # a broken status provider must never break the probe
        ok, checks = tm.get_telemetry().health_report()
        try:
            import jax

            n_dev = len(jax.devices())
            checks["devices"] = {"ok": n_dev > 0, "detail": f"{n_dev} visible"}
            ok = ok and n_dev > 0
        except Exception as e:
            checks["devices"] = {"ok": False, "detail": repr(e)}
            ok = False
        body = {"status": "ok" if ok else "unhealthy", "checks": checks}
        try:
            import sys

            # sys.modules guard (same as telemetry._collect_elastic): the
            # section is only ever non-empty once elastic.py is imported,
            # and a liveness probe must not pay a jax-heavy package import
            _elastic = sys.modules.get("deeplearning4j_tpu.parallel.elastic")
            status = _elastic.current_status() if _elastic else {}
            if status:
                body["elastic"] = status
        except Exception:
            pass  # a broken status provider must never break the probe
        try:
            import sys

            # serving section (docs/SERVING.md): per-model queue depth,
            # p50/p99 latency, shed counts, drain state — same sys.modules
            # guard as elastic, so a liveness probe never imports serving
            _serving = sys.modules.get("deeplearning4j_tpu.serving.router")
            status = _serving.current_status() if _serving else {}
            if status:
                body["serving"] = status
        except Exception:
            pass
        try:
            import sys

            # fleet section (docs/SERVING.md#fleet): ring membership,
            # per-worker health/in-flight/restarts, routing counters —
            # same sys.modules guard, only the front-tier process pays
            _fleet = sys.modules.get("deeplearning4j_tpu.serving.fleet")
            status = _fleet.current_status() if _fleet else {}
            if status:
                body["fleet"] = status
        except Exception:
            pass
        try:
            import sys

            # autotuning section (docs/AUTOTUNE.md): database dir, entry
            # count, lookup/hit/measurement counters — same sys.modules
            # guard, so a liveness probe never imports the tuner
            _tuning = sys.modules.get("deeplearning4j_tpu.tuning.database")
            status = _tuning.current_status() if _tuning else {}
            if status:
                body["tuning"] = status
        except Exception:
            pass
        if slo_status:
            body["slo"] = slo_status
        return json.dumps(body), ok

    # ------------------------------------------------------------- rendering
    def _render(self, session: "Optional[str]" = None) -> str:
        """DL4J overview-page parity: score chart, update:param-ratio chart
        (the reference's signature training-health plot), per-layer param
        stddevs, iteration timing — all inline SVG, zero JS dependencies.
        Multi-session browsing (VertxUIServer session selector): every
        session attached to any storage gets its own page."""
        import math

        sessions = self._sessions()
        if session is None and len(sessions) > 1:
            session = self._newest_session()
        recs = self._records(session)
        scores = [(r["iteration"], r["score"]) for r in recs if "score" in r]
        charts = [_line_chart(scores, "model score vs iteration")]

        # log10 mean(|update| l2 / |param| l2) — DL4J's "update:parameter
        # ratio" chart; healthy training sits near 1e-3
        ratios = []
        for r in recs:
            ps, us = r.get("params"), r.get("updates")
            if not ps or not us:
                continue
            vals = [us[k]["l2"] / ps[k]["l2"]
                    for k in us if ps.get(k, {}).get("l2", 0) > 0]
            if vals:
                ratios.append((r["iteration"],
                               math.log10(sum(vals) / len(vals) + 1e-12)))
        if ratios:
            charts.append(_line_chart(
                ratios, "log10 update:parameter ratio (mean over params)"))

        # per-layer parameter stddev over time (multi-series)
        series: dict = {}
        for r in recs:
            for k, s in (r.get("params") or {}).items():
                if k.endswith(".W") or k.endswith(".gamma"):
                    series.setdefault(k, []).append((r["iteration"], s["std"]))
        if series:
            charts.append(_multi_line_chart(series,
                                            "parameter stddev by layer"))

        times = [(r["iteration"], r["iter_ms"]) for r in recs
                 if isinstance(r.get("iter_ms"), (int, float))]
        if times:
            charts.append(_line_chart(times, "iteration time (ms)"))

        def ms(r):
            v = r.get("iter_ms")
            return f"{v:.1f}" if isinstance(v, (int, float)) else ""

        rows = "".join(
            f"<tr><td>{r.get('iteration', '')}</td><td>{r.get('epoch', '')}</td>"
            f"<td>{r['score']:.6f}</td><td>{ms(r)}</td></tr>"
            for r in recs[-25:] if isinstance(r.get("score"), (int, float))
        )
        import html as _html
        from urllib.parse import quote

        charts_html = "".join(f"<div>{c}</div>" for c in charts)
        nav = ""
        if sessions:
            links = " | ".join(
                (f"<b>{_html.escape(s)}</b>" if s == session else
                 f'<a href="/train/session/{quote(s, safe="")}">'
                 f"{_html.escape(s)}</a>")
                for s in sessions)
            nav = f"<p>sessions: {links}</p>"
        title = (f"Training overview — {_html.escape(session)}"
                 if session else "Training overview")
        qs = f"?session={quote(session, safe='')}" if session else ""
        return f"""<!doctype html><html><head><title>Training UI</title>
<meta http-equiv="refresh" content="5"></head>
<body style="font-family:sans-serif">
<h2>{title}</h2>{nav}{charts_html}
<h3>Recent iterations</h3>
<table border=1 cellpadding=4>
<tr><th>iter</th><th>epoch</th><th>score</th><th>ms</th></tr>{rows}</table>
<p>{len(recs)} records; raw data at <a href="/train/data">/train/data</a>;
per-layer <a href="/train/histograms{qs}">parameter/update histograms</a>;
<a href="/train/system">system</a></p>
</body></html>"""

    def _render_histograms(self, session: "Optional[str]" = None) -> str:
        """DL4J model-page parity (VERDICT r3 missing #5): per-layer
        parameter AND update histograms from the latest stats record (the
        reference renders the selected iteration; latest is the live view)."""
        import html as _html

        if session is None:
            session = self._newest_session()
        recs = self._records(session)
        latest = None
        for r in reversed(recs):
            if any("hist" in s
                   for key in ("params", "updates", "activations")
                   for s in (r.get(key) or {}).values()):
                latest = r
                break
        if latest is None:
            body = "<p>(no histogram data yet — StatsListener with " \
                   "collect_histograms=True populates this page)</p>"
        else:
            blocks = []
            for title, key in (("Parameters", "params"),
                               ("Updates", "updates"),
                               ("Activations", "activations")):
                charts = []
                for name, s in sorted((latest.get(key) or {}).items()):
                    if "hist" in s:
                        charts.append(_bar_chart(
                            s["hist"], s["hist_range"],
                            f"{name}  (mean {s['mean']:.2e}, std "
                            f"{s['std']:.2e})"))
                if charts:
                    blocks.append(f"<h3>{title} — iteration "
                                  f"{latest.get('iteration')}</h3>"
                                  + "".join(charts))
            body = "".join(blocks) or "<p>(no histogram data yet)</p>"
        title = ("Histograms — " + _html.escape(session)) if session \
            else "Histograms"
        return f"""<!doctype html><html><head><title>{title}</title>
<meta http-equiv="refresh" content="10"></head>
<body style="font-family:sans-serif">
<h2>{title}</h2>
<p><a href="/train/">&larr; overview</a></p>
{body}
</body></html>"""

    def _render_system(self) -> str:
        """DL4J UI "System" tab parity: hardware/memory facts — host RAM,
        process RSS, accelerator devices with per-device memory stats
        (the reference shows JVM/off-heap memory + GPU list; here it is
        host + PJRT devices). Each page load appends an RSS sample so the
        chart shows live memory over time."""
        import html as _html
        import time as _time

        snap = _system_snapshot()
        self._sys_history.append((_time.time(), snap.get("process_rss_mb")))
        self._sys_history = self._sys_history[-500:]
        t0 = self._sys_history[0][0]
        pts = [(t - t0, v) for t, v in self._sys_history
               if isinstance(v, int)]
        chart = _line_chart(pts, "process RSS (MB) vs seconds") if pts \
            else ""
        host_rows = "".join(
            f"<tr><td>{_html.escape(str(k))}</td>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in snap.items() if k != "devices")
        dev_rows = "".join(
            "<tr>" + "".join(
                f"<td>{_html.escape(str(d.get(c, '')))}</td>"
                for c in ("id", "platform", "kind", "mem_in_use_mb",
                          "mem_limit_mb")) + "</tr>"
            for d in snap.get("devices", []))
        return f"""<!doctype html><html><head><title>System</title>
<meta http-equiv="refresh" content="10"></head>
<body style="font-family:sans-serif">
<h2>System</h2>
<p><a href="/train/">&larr; overview</a></p>
{chart}
<h3>Host</h3><table border=1 cellpadding=4>{host_rows}</table>
<h3>Devices</h3><table border=1 cellpadding=4>
<tr><th>id</th><th>platform</th><th>kind</th><th>mem in use (MB)</th>
<th>mem limit (MB)</th></tr>{dev_rows}</table>
</body></html>"""


def _system_snapshot() -> dict:
    """Host + device facts for the System page (and tests)."""
    import platform
    import sys as _sys

    snap: dict = {"python": _sys.version.split()[0],
                  "platform": platform.platform()}
    try:  # host memory via /proc (Linux; this image)
        with open("/proc/meminfo") as f:
            mem = {l.split(":")[0]: l.split()[1] for l in f if ":" in l}
        snap["host_mem_total_mb"] = int(mem.get("MemTotal", 0)) // 1024
        snap["host_mem_available_mb"] = int(
            mem.get("MemAvailable", 0)) // 1024
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    snap["process_rss_mb"] = int(line.split()[1]) // 1024
    except OSError:
        pass
    try:
        import jax

        snap["jax"] = jax.__version__
        devs = []
        for d in jax.devices():
            row = {"id": d.id, "platform": d.platform,
                   "kind": getattr(d, "device_kind", "?")}
            try:
                stats = d.memory_stats() or {}
                if "bytes_in_use" in stats:
                    row["mem_in_use_mb"] = stats["bytes_in_use"] // 2**20
                if "bytes_limit" in stats:
                    row["mem_limit_mb"] = stats["bytes_limit"] // 2**20
            except Exception:
                pass
            devs.append(row)
        snap["devices"] = devs
    except Exception:
        snap["devices"] = []
    return snap


_PALETTE = ["#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
            "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf"]


def _bar_chart(counts, value_range, label, w=420, h=120, pad=24) -> str:
    """Histogram bars → inline SVG (DL4J histogram panels)."""
    import html as _html

    if not counts or max(counts) == 0:
        return "<p>(empty histogram)</p>"
    n = len(counts)
    peak = max(counts)
    bw = (w - 2 * pad) / n
    bars = []
    for i, c in enumerate(counts):
        bh = (h - 2 * pad) * c / peak
        bars.append(
            f'<rect x="{pad + i * bw:.1f}" y="{h - pad - bh:.1f}" '
            f'width="{max(bw - 1, 1):.1f}" height="{bh:.1f}" '
            f'fill="{_PALETTE[0]}"/>')
    lo, hi = value_range
    return (
        f'<svg width="{w}" height="{h + 16}" '
        f'xmlns="http://www.w3.org/2000/svg">'
        f'<text x="{pad}" y="14" font-size="11">{_html.escape(label)}</text>'
        f'<g transform="translate(0,10)">{"".join(bars)}'
        f'<text x="{pad}" y="{h - 4}" font-size="10">{lo:.3g}</text>'
        f'<text x="{w - pad}" y="{h - 4}" font-size="10" '
        f'text-anchor="end">{hi:.3g}</text></g></svg>')


def _multi_line_chart(series, label, w=640, h=240, pad=40) -> str:
    """Named series → one SVG with a legend (DL4J per-layer charts)."""
    allpts = [p for pts in series.values() for p in pts]
    if not allpts:
        return "<p>(no data yet)</p>"
    x0, x1 = min(p[0] for p in allpts), max(p[0] for p in allpts)
    y0, y1 = min(p[1] for p in allpts), max(p[1] for p in allpts)
    if y1 == y0:
        y1 = y0 + 1.0
    sx = lambda x: pad + (x - x0) / max(x1 - x0, 1) * (w - 2 * pad)
    sy = lambda y: h - pad - (y - y0) / (y1 - y0) * (h - 2 * pad)
    lines, legend = [], []
    for i, (name, pts) in enumerate(sorted(series.items())):
        color = _PALETTE[i % len(_PALETTE)]
        coords = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in pts)
        lines.append(f'<polyline fill="none" stroke="{color}" '
                     f'stroke-width="1.2" points="{coords}"/>')
        legend.append(f'<tspan x="{w - pad + 4}" dy="14" '
                      f'fill="{color}">{name}</tspan>')
    return (f'<svg width="{w + 160}" height="{h}">'
            f'<rect width="{w}" height="{h}" fill="#fafafa" stroke="#ccc"/>'
            + "".join(lines)
            + f'<text x="{w // 2}" y="16" font-size="13" '
              f'text-anchor="middle">{label}</text>'
            + f'<text x="{w - pad + 4}" y="24" font-size="10">{"".join(legend)}</text>'
            + f'<text x="4" y="{pad}" font-size="11">{y1:.4g}</text>'
            + f'<text x="4" y="{h - pad}" font-size="11">{y0:.4g}</text></svg>')


def _line_chart(points, label, w=640, h=240, pad=40) -> str:
    if not points:
        return "<p>(no data yet)</p>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if y1 == y0:
        y1 = y0 + 1.0
    sx = lambda x: pad + (x - x0) / max(x1 - x0, 1) * (w - 2 * pad)
    sy = lambda y: h - pad - (y - y0) / (y1 - y0) * (h - 2 * pad)
    pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in points)
    return (f'<svg width="{w}" height="{h}">'
            f'<rect width="{w}" height="{h}" fill="#fafafa" stroke="#ccc"/>'
            f'<polyline fill="none" stroke="#1f77b4" stroke-width="1.5" '
            f'points="{pts}"/>'
            f'<text x="{pad}" y="{h - 8}" font-size="11">{x0}</text>'
            f'<text x="{w - pad}" y="{h - 8}" font-size="11" '
            f'text-anchor="end">{x1}</text>'
            f'<text x="4" y="{pad}" font-size="11">{y1:.4g}</text>'
            f'<text x="4" y="{h - pad}" font-size="11">{y0:.4g}</text>'
            f'<text x="{w // 2}" y="16" font-size="13" '
            f'text-anchor="middle">{label}</text></svg>')
