"""SLO engine: declared objectives, error budgets, multi-window burn rates
(docs/OBSERVABILITY.md#request-tracing--slos).

The serving tier's CI gates (``serving_p99_latency_ms``, ``serving_qps``)
answer "did this round regress?"; an SLO answers "is production meeting its
promise *right now*, and how fast is it spending the error budget?" — the
SRE formulation. This module declares objectives over the telemetry
registry (util/telemetry.py) and evaluates them on demand:

- **availability** — good / (good + bad) from the ``serving.completed_total``
  vs ``serving.shed_total`` + ``serving.request_errors_total`` counters,
  optionally filtered by ``model``/``lane`` labels. The error budget is
  ``1 - target``; the burn rate over a window is the window's bad fraction
  divided by the budget (burn 1.0 = spending exactly the budget; 10 = ten
  times too fast).
- **latency_p99** — the live ``serving.latency_p99_seconds`` gauge (worst
  matching series when the filter spans several) against a millisecond
  bound. Each evaluation is one compliance sample; the burn rate over a
  window is the fraction of non-compliant samples divided by the budget
  (the allowed non-compliant fraction, default 5%).

Burn rates are computed over EVERY window in ``objective.windows``
(default 1m/5m/1h — the multiwindow alerting pattern), from snapshots the
engine itself records at each ``evaluate()``; callers that want fresh
windows poll ``evaluate()`` (the ``/metrics`` collector and the
``/slo``/``/healthz`` routes do).

When the **longest window's budget is exhausted** (remaining < 0 — burning
strictly faster than the allowed rate; burn exactly 1.0 is compliant) the
objective flips its ``slo.<name>`` health check — ``/healthz`` answers 503
so the deploy/rollback machinery reacts without parsing burn math — emits
a ``TrainingHealthMonitor``-style anomaly (``slo.anomalies_total{type=
budget_exhausted}`` + an instant trace event, via
``util.health.record_anomaly``), and invokes any ``on_breach`` hooks.
Recovery flips the check back and counts a ``budget_recovered`` anomaly.

Surfaces: ``GET /slo`` (ModelServer + UIServer), the ``slo`` section on
``/healthz`` (sys.modules-guarded like elastic/serving/tuning — a process
that never imported this module pays nothing), and scrape-time
``slo.compliant`` / ``slo.burn_rate{window=}`` / ``slo.error_budget_
remaining`` gauges on ``/metrics``.

    from deeplearning4j_tpu.util import slo
    slo.register(slo.SloObjective("dense-availability", "availability",
                                  target=0.999, model="dense"))
    slo.register(slo.SloObjective("dense-p99", "latency_p99", target=25.0,
                                  model="dense", lane="interactive"))
    slo.get_engine().evaluate()
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.util import telemetry as tm

#: multiwindow burn-rate intervals, seconds (1m / 5m / 1h)
DEFAULT_WINDOWS = (60.0, 300.0, 3600.0)

#: default allowed non-compliance fraction for latency objectives
DEFAULT_LATENCY_BUDGET = 0.05

KINDS = ("availability", "latency_p99")


@dataclasses.dataclass
class SloObjective:
    """One declared objective. ``target`` is an availability fraction
    (e.g. 0.999) for kind="availability", or a p99 bound in MILLISECONDS
    for kind="latency_p99". ``model``/``lane`` filter the telemetry
    series (None = all). ``budget`` overrides the error budget — the
    allowed bad fraction (defaults: ``1 - target`` for availability,
    :data:`DEFAULT_LATENCY_BUDGET` for latency)."""

    name: str
    kind: str
    target: float
    model: Optional[str] = None
    lane: Optional[str] = None
    windows: Tuple[float, ...] = DEFAULT_WINDOWS
    budget: Optional[float] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r} "
                             f"(have {KINDS})")
        if self.kind == "availability" and not 0.0 < self.target <= 1.0:
            raise ValueError(f"availability target must be in (0, 1], "
                             f"got {self.target}")
        if self.kind == "latency_p99" and self.target <= 0:
            raise ValueError(f"latency_p99 target must be > 0 ms, "
                             f"got {self.target}")
        if not self.windows:
            raise ValueError("need at least one burn window")
        self.windows = tuple(sorted(float(w) for w in self.windows))

    def error_budget(self) -> float:
        if self.budget is not None:
            return max(1e-9, float(self.budget))
        if self.kind == "availability":
            return max(1e-9, 1.0 - self.target)
        return DEFAULT_LATENCY_BUDGET

    def _labels(self) -> dict:
        lab = {}
        if self.model is not None:
            lab["model"] = self.model
        if self.lane is not None:
            lab["lane"] = self.lane
        return lab


def _window_label(w: float) -> str:
    return f"{int(w)}s" if w == int(w) else f"{w}s"


class SloEngine:
    """Objective registry + evaluator (module singleton via
    :func:`get_engine`; ``clock`` is injectable for tests)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self.objectives: Dict[str, SloObjective] = {}
        # name -> deque[(t, good_cum, bad_cum)] (availability)
        #         deque[(t, bad 0/1, value_ms)] (latency)
        self._samples: Dict[str, deque] = {}
        self._exhausted: Dict[str, bool] = {}
        self._hooks: List[Callable[[str, str], None]] = []
        self._recover_hooks: List[Callable[[str], None]] = []

    # -------------------------------------------------------------- registry
    def register(self, objective: SloObjective) -> SloObjective:
        with self._lock:
            if objective.name in self.objectives:
                raise ValueError(f"SLO {objective.name!r} already declared")
            self.objectives[objective.name] = objective
            self._samples[objective.name] = deque()
            self._exhausted[objective.name] = False
        tm.counter("slo.objectives_registered_total")
        tm.set_health(f"slo.{objective.name}", True, "registered")
        return objective

    def on_breach(self, hook: Callable[[str, str], None]):
        """``hook(objective_name, detail)`` invoked on budget exhaustion
        (the TrainingHealthMonitor ``on_anomaly`` convention)."""
        with self._lock:
            if hook not in self._hooks:
                self._hooks.append(hook)

    def on_recover(self, hook: Callable[[str], None]):
        """``hook(objective_name)`` invoked when an exhausted objective's
        budget recovers — the other half of the breach seam, so state
        machines hung off the SLO engine (the serving brownout controller,
        serving/resilience.py) can restore service symmetrically."""
        with self._lock:
            if hook not in self._recover_hooks:
                self._recover_hooks.append(hook)

    def off_breach(self, hook):
        """Remove a hook registered with :meth:`on_breach` (the other half
        of install/uninstall symmetry — serving/resilience.py)."""
        with self._lock:
            if hook in self._hooks:
                self._hooks.remove(hook)

    def off_recover(self, hook):
        """Remove a hook registered with :meth:`on_recover`."""
        with self._lock:
            if hook in self._recover_hooks:
                self._recover_hooks.remove(hook)

    def reset(self):
        """Drop every objective and restore its health check (tests, and
        the smoke's synthetic budget-exhausted case). Objectives that are
        exhausted at reset time fire their recover hooks first — dropping
        an objective ends its breach, and a state machine hung off the
        engine (the serving brownout controller) must see the recovery,
        not stay browned out forever with the hook list emptied under it."""
        with self._lock:
            names = list(self.objectives)
            exhausted = [n for n, bad in self._exhausted.items() if bad]
            recover_hooks = list(self._recover_hooks)
            self.objectives.clear()
            self._samples.clear()
            self._exhausted.clear()
            self._hooks.clear()
            self._recover_hooks.clear()
        for name in exhausted:
            for hook in recover_hooks:
                try:
                    hook(name)
                except Exception:
                    pass  # a broken hook must never break reset
        for name in names:
            tm.set_health(f"slo.{name}", True, "slo reset")

    # ------------------------------------------------------------ measurement
    def _observe(self, obj: SloObjective, now: float):
        """Record one sample for the objective and prune beyond the
        longest window."""
        tele = tm.get_telemetry()
        buf = self._samples[obj.name]
        lab = obj._labels()
        if obj.kind == "availability":
            good = tele.counter_total("serving.completed_total", **lab)
            bad = tele.counter_total("serving.shed_total", **lab) \
                + tele.counter_total("serving.request_errors_total", **lab)
            buf.append((now, good, bad))
        else:
            vals = tele.gauge_values("serving.latency_p99_seconds", **lab)
            val_ms = max(vals) * 1e3 if vals else None
            bad = 0 if val_ms is None or val_ms <= obj.target else 1
            buf.append((now, bad, val_ms))
        horizon = now - obj.windows[-1] - 1.0
        while len(buf) > 1 and buf[1][0] <= horizon:
            buf.popleft()

    def _window_stats(self, obj: SloObjective, now: float,
                      window: float) -> dict:
        """Bad fraction + burn rate over one window from the sample buffer."""
        buf = self._samples[obj.name]
        cutoff = now - window
        budget = obj.error_budget()
        if obj.kind == "availability":
            # baseline = the NEWEST sample at-or-before the window start
            # (the prune in _observe keeps exactly one such sample):
            # counter deltas against it cover everything that happened
            # inside the window. Using the first in-window sample instead
            # would fold traffic recorded between the window start and
            # that sample into the baseline — bad events would age out up
            # to one poll interval early and flap /healthz back to 200
            # while still inside the declared window.
            base = None
            for t, good, bad in reversed(buf):
                if t <= cutoff:
                    base = (good, bad)
                    break
            cur = (buf[-1][1], buf[-1][2]) if buf else (0.0, 0.0)
            if base is None:
                # every sample is inside the window (young process):
                # delta since the first observation
                base = (buf[0][1], buf[0][2]) if buf else cur
            d_good = max(0.0, cur[0] - base[0])
            d_bad = max(0.0, cur[1] - base[1])
            total = d_good + d_bad
            bad_frac = (d_bad / total) if total > 0 else 0.0
            out = {"good": d_good, "bad": d_bad}
        else:
            pts = [(b, v) for t, b, v in buf if t >= cutoff and v is not None]
            bad_frac = (sum(b for b, _v in pts) / len(pts)) if pts else 0.0
            out = {"samples": len(pts)}
        out["bad_fraction"] = round(bad_frac, 6)
        out["burn_rate"] = round(bad_frac / budget, 4)
        return out

    # -------------------------------------------------------------- evaluate
    def evaluate(self, now: Optional[float] = None) -> dict:
        """Evaluate every objective: record a fresh sample, compute
        current compliance + per-window burn rates + remaining budget,
        flip the ``slo.<name>`` health checks, fire breach hooks. Returns
        the JSON-able ``/slo`` document."""
        now = self.clock() if now is None else now
        with self._lock:
            objectives = list(self.objectives.values())
        results = []
        for obj in objectives:
            with self._lock:
                self._observe(obj, now)
                windows = {
                    _window_label(w): self._window_stats(obj, now, w)
                    for w in obj.windows}
                buf = self._samples[obj.name]
                if obj.kind == "availability":
                    good, bad = buf[-1][1], buf[-1][2]
                    total = good + bad
                    current = (good / total) if total > 0 else None
                    compliant = current is None or current >= obj.target
                else:
                    current = buf[-1][2]
                    compliant = current is None or current <= obj.target
            longest = windows[_window_label(obj.windows[-1])]
            remaining = round(1.0 - longest["burn_rate"], 4)
            # strictly negative: burning EXACTLY at the allowed rate
            # (burn 1.0) is a service meeting its SLO to the decimal —
            # flipping /healthz to 503 there would drain a compliant
            # service at its own declared boundary
            exhausted = remaining < 0.0
            res = {
                "name": obj.name, "kind": obj.kind, "target": obj.target,
                "model": obj.model, "lane": obj.lane,
                "budget": obj.error_budget(),
                "current": None if current is None else round(current, 6),
                "compliant": compliant,
                "windows": windows,
                "budget_remaining": remaining,
                "exhausted": exhausted,
            }
            self._transition(obj, res)
            results.append(res)
        return {"time": time.time(), "objectives": results}

    def _transition(self, obj: SloObjective, res: dict):
        """Health-check + anomaly bookkeeping on exhaustion transitions."""
        from deeplearning4j_tpu.util.health import record_anomaly

        with self._lock:
            was = self._exhausted.get(obj.name, False)
            self._exhausted[obj.name] = res["exhausted"]
            hooks = list(self._hooks)
            recover_hooks = list(self._recover_hooks)
        if res["exhausted"]:
            detail = (f"error budget exhausted: burn "
                      f"{res['windows'][_window_label(obj.windows[-1])]['burn_rate']}x "
                      f"over {_window_label(obj.windows[-1])} "
                      f"(target {obj.target}, budget {res['budget']})")
            tm.set_health(f"slo.{obj.name}", False, detail)
            if not was:
                record_anomaly("budget_exhausted", f"{obj.name}: {detail}",
                               source="slo", slo=obj.name)
                for hook in hooks:
                    try:
                        hook(obj.name, detail)
                    except Exception:
                        pass  # a broken hook must never break evaluation
        else:
            tm.set_health(f"slo.{obj.name}", True,
                          f"budget remaining {res['budget_remaining']}")
            if was:
                record_anomaly("budget_recovered", obj.name, source="slo",
                               slo=obj.name)
                for hook in recover_hooks:
                    try:
                        hook(obj.name)
                    except Exception:
                        pass  # a broken hook must never break evaluation


# ------------------------------------------------------------- module API
_engine: Optional[SloEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SloEngine:
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = SloEngine()
    return _engine


def register(objective: SloObjective) -> SloObjective:
    """Declare an objective on the process engine and make sure the
    scrape-time gauges are installed."""
    tm.install_default_collectors()
    return get_engine().register(objective)


def reset():
    if _engine is not None:
        _engine.reset()


def current_status() -> dict:
    """The ``/healthz`` slo section (and ``/slo`` body): empty dict when
    nothing is declared, so the probe stays cheap."""
    eng = _engine
    if eng is None or not eng.objectives:
        return {}
    return eng.evaluate()


def collect_slo_gauges() -> list:
    """Scrape-time gauges for the telemetry default collectors
    (sys.modules-guarded in util/telemetry.py like elastic/serving)."""
    eng = _engine
    if eng is None or not eng.objectives:
        return []
    doc = eng.evaluate()
    rows: list = [("slo.objectives", {}, float(len(doc["objectives"])))]
    for res in doc["objectives"]:
        lab = {"slo": res["name"]}
        rows.append(("slo.compliant", lab,
                     1.0 if res["compliant"] else 0.0))
        rows.append(("slo.error_budget_remaining", lab,
                     float(res["budget_remaining"])))
        if res["current"] is not None:
            rows.append(("slo.current", lab, float(res["current"])))
        for wlabel, ws in res["windows"].items():
            rows.append(("slo.burn_rate", {**lab, "window": wlabel},
                         float(ws["burn_rate"])))
    return rows
