"""On-disk AOT lowering store — the cross-process HALF the persistent
compilation cache cannot cover.

``jax_compilation_cache_dir`` (util/compile_cache.py) removes the BACKEND
compile from a warm process, but the warm process still pays the full
Python trace + jaxpr→MLIR lowering (several seconds for the flagship
topology — the dominant term once the backend compile is cached). This
store serializes the LOWERED module (``jax.export``) keyed by everything
the trace depends on; a warm process deserializes StableHLO instead of
re-tracing, and its backend compile then hits the persistent cache — the
full compile-once chain across processes.

Key = sha256 of (function tag, model conf JSON, call signature,
jax/jaxlib versions, a content digest of the deeplearning4j_tpu package
sources, and the tracing-relevant Environment flags). Any code or config
change misses cleanly and re-exports — a stale entry can never be loaded.

Trade-off: ``Exported.call`` does NOT preserve buffer donation, so a
loaded train step keeps an extra copy of params/opt-state alive per step.
Right for serving cold starts and short fine-tunes; for long training runs
on memory-tight chips, prefer plain ``warmup()`` (in-process AOT keeps
donation) and let only the backend cache work across processes.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Optional

_pkg_digest_cache: Optional[str] = None


def aot_build(store: Optional["AotStore"], tag: str, conf_json: str, sig,
              jit_fn, args, kwargs):
    """One AOT executable for a warmup signature, shared by
    MultiLayerNetwork and ComputationGraph: from the lowering store when
    available (deserialize, NO re-trace), else trace+lower+compile —
    exporting to the store along the way so the next process skips the
    trace."""
    if store is None:
        return jit_fn.lower(*args, **kwargs).compile()
    key = store.key(tag, conf_json, sig)
    fn = store.load(key)
    if fn is None:
        from jax import export as jexport

        exported = jexport.export(jit_fn)(*args, **kwargs)
        store.save(key, exported)
        fn = exported.call
    return fn


def _tm():
    from deeplearning4j_tpu.util import telemetry

    return telemetry


def package_digest() -> str:
    """Content digest of every .py file in the deeplearning4j_tpu package —
    part of the store key, so ANY code change invalidates (the traced
    program can depend on any module). ~2 MB of source, computed once per
    process."""
    global _pkg_digest_cache
    if _pkg_digest_cache is None:
        import deeplearning4j_tpu

        root = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                h.update(os.path.relpath(path, root).encode())
                with open(path, "rb") as fh:
                    h.update(fh.read())
        _pkg_digest_cache = h.hexdigest()
    return _pkg_digest_cache


class AotStore:
    """Directory of serialized ``jax.export`` modules, loaded by exact key."""

    def __init__(self, directory: str):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)

    def key(self, tag: str, conf_json: str, sig) -> str:
        import jax

        h = hashlib.sha256()
        for part in (tag, conf_json, repr(sig), jax.__version__,
                     getattr(jax, "__version_info__", ""),
                     package_digest(), self._env_bits()):
            h.update(repr(part).encode())
        return h.hexdigest()

    @staticmethod
    def _env_bits() -> str:
        """Environment flags that can alter the traced program."""
        from deeplearning4j_tpu.config import get_environment

        env = get_environment()
        return repr((env.debug, env.profiling, env.nan_panic,
                     env.default_compute_dtype))

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.jaxexport")

    def load(self, key: str) -> Optional[Callable]:
        """Deserialize the lowered module for ``key`` -> callable, or None.
        The callable re-compiles the stored StableHLO on first use (a
        persistent-cache hit when that is enabled) — no Python re-trace.
        Hits/misses feed the telemetry registry (``aot_store.hits_total`` /
        ``aot_store.misses_total`` on /metrics)."""
        path = self._path(key)
        if not os.path.exists(path):
            _tm().counter("aot_store.misses_total")
            return None
        from jax import export as jexport

        try:
            with open(path, "rb") as fh:
                exported = jexport.deserialize(fh.read())
        except Exception:
            _tm().counter("aot_store.misses_total")
            return None  # truncated/incompatible blob: treat as a miss
        _tm().counter("aot_store.hits_total")
        return exported.call

    def save(self, key: str, exported) -> str:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(exported.serialize())
        os.replace(tmp, path)  # atomic: concurrent processes race safely
        _tm().counter("aot_store.saves_total")
        return path

    def entries(self) -> int:
        return sum(1 for f in os.listdir(self.dir)
                   if f.endswith(".jaxexport"))
