"""KD-tree for low-dimensional nearest-neighbour search.

Reference parity: org.deeplearning4j.clustering.kdtree.KDTree (path-cite,
mount empty this round). Host-side pointer structure like the reference;
the box-pruning bound is the same quantity as the registered
``knn_mindistance`` op.
"""

from __future__ import annotations

import heapq

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis):
        self.index = index
        self.axis = axis
        self.left = None
        self.right = None


class KDTree:
    def __init__(self, items):
        self.items = np.asarray(items, np.float64)
        if self.items.ndim != 2:
            raise ValueError("items must be (N, D)")
        self.dims = self.items.shape[1]
        self.root = self._build(list(range(len(self.items))), 0)

    def _build(self, idx, depth):
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.items[i, axis])
        mid = len(idx) // 2
        node = _KDNode(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def query(self, x, k: int = 1):
        """(indices, distances) of the k nearest, euclidean, ascending."""
        x = np.asarray(x, np.float64)
        heap = []  # max-heap of (-dist, index)

        def search(node):
            if node is None:
                return
            p = self.items[node.index]
            d = float(np.linalg.norm(x - p))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = x[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff <= 0 else \
                (node.right, node.left)
            search(near)
            tau = -heap[0][0] if len(heap) == k else np.inf
            if abs(diff) < tau:   # hypersphere crosses the splitting plane
                search(far)

        search(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return ([i for _, i in out], [d for d, _ in out])
