"""KMeans — jitted Lloyd iterations.

Reference parity: org.deeplearning4j.clustering.kmeans.KMeansClustering
(+ ClusterSet / ClusterUtils, path-cite, mount empty this round): k-means
with a max-iteration and a distance-convergence termination, returning
cluster centers + point assignments.

TPU-native design: the whole optimization is ONE compiled program — the
(N, K) distance matrix is a single MXU matmul-shaped computation per
iteration inside ``lax.fori_loop``; centers update by segment mean
(one-hot matmul, MXU again). k-means++ seeding runs as a short host loop
of device argmax calls (K is small).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _sq_dists(x, c):
    """(N, K) squared euclidean distances via the expanded form — the
    x @ c.T term is the MXU workload."""
    xx = jnp.sum(x * x, axis=1, keepdims=True)        # (N, 1)
    cc = jnp.sum(c * c, axis=1)[None, :]              # (1, K)
    return jnp.maximum(xx + cc - 2.0 * (x @ c.T), 0.0)


class KMeans:
    """KMeansClustering-parity estimator.

    >>> km = KMeans(k=3, max_iterations=100).fit(x)
    >>> labels = km.predict(x); centers = km.centers
    """

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 init: str = "kmeans++", seed: int = 0):
        if init not in ("kmeans++", "random"):
            raise ValueError(f"unknown init {init!r}")
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.tol = float(tol)
        self.init = init
        self.seed = int(seed)
        self.centers = None
        self.inertia = None

    # -- seeding -------------------------------------------------------------
    def _seed_centers(self, x):
        key = jax.random.PRNGKey(self.seed)
        n = x.shape[0]
        if self.init == "random":
            idx = jax.random.choice(key, n, (self.k,), replace=False)
            return x[idx]
        # k-means++: each next center sampled ∝ squared distance to the set
        key, sub = jax.random.split(key)
        first = jax.random.randint(sub, (), 0, n)
        centers = [x[first]]
        d2 = jnp.sum((x - centers[0]) ** 2, axis=1)
        for _ in range(1, self.k):
            key, sub = jax.random.split(key)
            p = d2 / jnp.maximum(jnp.sum(d2), 1e-12)
            nxt = jax.random.choice(sub, n, p=p)
            centers.append(x[nxt])
            d2 = jnp.minimum(d2, jnp.sum((x - centers[-1]) ** 2, axis=1))
        return jnp.stack(centers)

    # -- training ------------------------------------------------------------
    def fit(self, x):
        x = jnp.asarray(x, jnp.float32)
        c0 = self._seed_centers(x)

        @jax.jit
        def run(x, c0):
            def body(state):
                c, _, i, _ = state
                d = _sq_dists(x, c)
                assign = jnp.argmin(d, axis=1)                    # (N,)
                oh = jax.nn.one_hot(assign, self.k, dtype=x.dtype)  # (N, K)
                counts = jnp.sum(oh, axis=0)                      # (K,)
                sums = oh.T @ x                                   # (K, D)
                new_c = jnp.where(counts[:, None] > 0,
                                  sums / jnp.maximum(counts[:, None], 1.0),
                                  c)                               # keep empty
                shift = jnp.max(jnp.sum((new_c - c) ** 2, axis=1))
                return new_c, assign, i + 1, shift

            def cond(state):
                _, _, i, shift = state
                return (i < self.max_iterations) & (shift > self.tol ** 2)

            init = (c0, jnp.zeros(x.shape[0], jnp.int32), 0,
                    jnp.asarray(jnp.inf))
            c, assign, n_iter, _ = jax.lax.while_loop(cond, body, init)
            d = _sq_dists(x, c)
            assign = jnp.argmin(d, axis=1)
            inertia = jnp.sum(jnp.min(d, axis=1))
            return c, assign, inertia, n_iter

        c, assign, inertia, n_iter = run(x, c0)
        self.centers = np.asarray(c)
        self.labels = np.asarray(assign)
        self.inertia = float(inertia)
        self.n_iterations = int(n_iter)
        return self

    def predict(self, x):
        if self.centers is None:
            raise RuntimeError("fit() first")
        d = _sq_dists(jnp.asarray(x, jnp.float32),
                      jnp.asarray(self.centers))
        return np.asarray(jnp.argmin(d, axis=1))
