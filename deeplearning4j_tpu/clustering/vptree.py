"""Vantage-point tree for metric nearest-neighbour search.

Reference parity: org.deeplearning4j.clustering.vptree.VPTree (path-cite,
mount empty this round) — the index behind dl4j's nearest-neighbors server
and the original BarnesHutTsne neighbour search. Host-side by design, as in
the reference: the tree is a pointer structure serving latency-bound
queries, not device math (batch distance computations that DO belong on
device go through clustering.kmeans/_sq_dists-style matmuls instead).

Supported distances: euclidean, cosine (reference "euclidean"/"cosinesimilarity").
"""

from __future__ import annotations

import heapq

import numpy as np


def _euclidean(a, b):
    d = a - b
    return float(np.sqrt(np.dot(d, d)))


def _cosine(a, b):
    na = np.linalg.norm(a)
    nb = np.linalg.norm(b)
    if na == 0 or nb == 0:
        return 1.0
    return float(1.0 - np.dot(a, b) / (na * nb))


_DISTANCES = {"euclidean": _euclidean, "cosine": _cosine,
              "cosinesimilarity": _cosine}


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index):
        self.index = index
        self.threshold = 0.0
        self.inside = None
        self.outside = None


class VPTree:
    """VPTree(items).query(x, k) -> (indices, distances)."""

    def __init__(self, items, distance: str = "euclidean", seed: int = 0):
        self.items = np.asarray(items, np.float64)
        if self.items.ndim != 2:
            raise ValueError("items must be (N, D)")
        try:
            self._dist = _DISTANCES[distance]
        except KeyError:
            raise ValueError(f"unknown distance {distance!r}") from None
        self._rng = np.random.default_rng(seed)
        self.root = self._build(list(range(len(self.items))))

    def _build(self, idx):
        if not idx:
            return None
        # random vantage point (reference picks randomly too)
        vp_pos = self._rng.integers(0, len(idx))
        idx[0], idx[vp_pos] = idx[vp_pos], idx[0]
        node = _Node(idx[0])
        rest = idx[1:]
        if not rest:
            return node
        vp = self.items[node.index]
        dists = [self._dist(vp, self.items[i]) for i in rest]
        median = float(np.median(dists))
        node.threshold = median
        inside = [i for i, d in zip(rest, dists) if d <= median]
        outside = [i for i, d in zip(rest, dists) if d > median]
        if not outside and len(inside) > 1:
            # all distances tie at the median (duplicate-heavy data): the
            # metric cannot split, so split positionally to keep the tree
            # O(log n) deep instead of recursing once per point
            mid = len(inside) // 2
            inside, outside = inside[:mid], inside[mid:]
            # threshold stays = median: a query ball at distance <= median
            # must search both sides, which the crossing test already does
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def query(self, x, k: int = 1):
        """k nearest neighbours of ``x``: (indices, distances), ascending."""
        x = np.asarray(x, np.float64)
        heap = []  # max-heap of (-dist, index)

        def search(node):
            if node is None:
                return
            d = self._dist(x, self.items[node.index])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                search(node.inside)
                if d + tau > node.threshold:   # ball crosses the boundary
                    tau = -heap[0][0] if len(heap) == k else np.inf
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau <= node.threshold:
                    search(node.inside)

        search(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return ([i for _, i in out], [d for d, _ in out])
