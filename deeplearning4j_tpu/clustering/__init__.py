"""Clustering + nearest-neighbour search.

Reference parity: the ``deeplearning4j-nearestneighbors-parent`` module
family (org.deeplearning4j.clustering.kmeans.KMeansClustering,
clustering.vptree.VPTree, clustering.kdtree.KDTree,
clustering.lsh.RandomProjectionLSH — path-cites, mount empty this round).

TPU-native design: KMeans runs its Lloyd iterations as ONE jitted XLA
program (distance matrix on the MXU, lax.fori_loop over iterations) instead
of the reference's threaded JVM loop; the tree structures (VPTree/KDTree)
are host-side index structures exactly as in the reference — they serve
CPU-bound nearest-neighbour queries (the nearest-neighbors-server use case),
not device compute. LSH hashes with one device matmul and queries host-side.
"""

from deeplearning4j_tpu.clustering.kmeans import KMeans  # noqa: F401
from deeplearning4j_tpu.clustering.vptree import VPTree  # noqa: F401
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
from deeplearning4j_tpu.clustering.lsh import RandomProjectionLSH  # noqa: F401
