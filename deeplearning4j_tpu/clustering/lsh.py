"""Random-projection LSH for approximate cosine nearest neighbours.

Reference parity: org.deeplearning4j.clustering.lsh.RandomProjectionLSH
(path-cite, mount empty this round): sign-of-random-projection hashing for
cosine similarity. TPU-native: the (N, bits) projection is one device
matmul; bucket lookup is host-side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class RandomProjectionLSH:
    def __init__(self, hash_bits: int = 16, seed: int = 0):
        self.hash_bits = int(hash_bits)
        self.seed = int(seed)
        self._planes = None
        self._buckets = None
        self.items = None

    def _project(self, x):
        return np.asarray(jnp.asarray(x, jnp.float32) @ self._planes) > 0

    def fit(self, items):
        self.items = np.asarray(items, np.float32)
        d = self.items.shape[1]
        key = jax.random.PRNGKey(self.seed)
        self._planes = jnp.asarray(
            jax.random.normal(key, (d, self.hash_bits), jnp.float32))
        signs = self._project(self.items)            # (N, bits) bool
        self._codes = np.packbits(signs, axis=1)
        self._buckets = {}
        for i, code in enumerate(map(bytes, self._codes)):
            self._buckets.setdefault(code, []).append(i)
        return self

    def query(self, x, k: int = 1, max_probes: int = 8, oversample: int = 4):
        """Approximate k nearest by cosine: probe buckets in code-Hamming
        order until ``oversample * k`` candidates are gathered or
        ``max_probes`` distinct buckets were searched (a cap, not a floor —
        a dense first bucket satisfies a small query immediately). Returns
        (indices, cosine_distances)."""
        if self._buckets is None:
            raise RuntimeError("fit() first")
        x = np.asarray(x, np.float32)
        sign = self._project(x[None, :])[0]
        cands = []
        # rank stored codes by hamming distance to the query code
        q = np.unpackbits(np.packbits(sign))[:self.hash_bits]
        codes_bits = np.unpackbits(self._codes, axis=1)[:, :self.hash_bits]
        ham = np.sum(codes_bits != q[None, :], axis=1)
        order = np.argsort(ham, kind="stable")
        seen_codes = set()
        for i in order:
            code = bytes(self._codes[i])
            if code in seen_codes:
                continue
            seen_codes.add(code)
            cands.extend(self._buckets[code])
            if (len(cands) >= max(k, 1) * max(oversample, 1)
                    or len(seen_codes) >= max_probes):
                break
        if not cands:
            cands = list(range(len(self.items)))
        cand_arr = self.items[cands]
        na = np.linalg.norm(cand_arr, axis=1) * np.linalg.norm(x)
        cos = 1.0 - (cand_arr @ x) / np.maximum(na, 1e-12)
        top = np.argsort(cos, kind="stable")[:k]
        return [cands[i] for i in top], [float(cos[i]) for i in top]
