"""Runtime environment flags (Nd4jEnvironmentVars / ND4JSystemProperties /
native Environment parity — SURVEY.md §5.6 tiers (b) and (c)).

Three config tiers, mirroring the reference:
(a) model configs — Jackson-JSON builder DSL → `nn/conf.py` (JSON round-trip);
(b) runtime flags — environment variables read here at import and mutable at
    runtime through :class:`Environment` (the reference's
    ``Nd4j.getEnvironment()`` singleton);
(c) backend toggles — forwarded to JAX/XLA where an equivalent exists.

Recognized variables (DL4J_TPU_* namespace; reference names in comments):

- ``DL4J_TPU_DEBUG``       — verbose op logging hooks    (SD_DEBUG / debug mode)
- ``DL4J_TPU_VERBOSE``     — DEBUG-level logging on the 'deeplearning4j_tpu'
  logger (SD_VERBOSE)
- ``DL4J_TPU_PROFILING``   — install OpProfiler at import (profiling mode)
- ``DL4J_TPU_NAN_PANIC``   — raise on NaN/Inf op outputs  (ProfilerConfig.nanPanic)
- ``DL4J_TPU_COMPUTE_DTYPE`` — default compute dtype for new configs
  ("float32" | "bfloat16")   (ND4J default dtype)
- ``DL4J_TPU_REMAT_POLICY`` — default selective-remat policy for new configs
  ("none" | "full" | "save_conv" | … — see util/xla_tuning.py; TPU-native,
  no reference equivalent). The fusion-sweep harness uses this to A/B
  policies without code changes.
- ``DL4J_TPU_SYNC_EVERY`` — default ``sync_every`` for new configs (≥1):
  fit() fetches the per-step loss to the host every N steps and dispatches
  TrainingListener callbacks in coalesced batches instead of risking a
  device sync per iteration (docs/HOST_PIPELINE.md; TPU-native, no
  reference equivalent — the JVM listener bus had no device round-trip).
- ``DL4J_TPU_ETL_WORKERS`` — worker-process count for the multiprocess
  TransformProcess executor (datavec/executor.py); 0/unset = one per host
  core, capped at 8 (the reference sizes Spark executors the same way).
- ``DL4J_TPU_BUCKETS`` — default shape-bucketing spec for new configs
  ("pow2" | "batch=8,16,32;seq=pow2" — data/bucketing.py,
  docs/COMPILE_CACHE.md): ragged batches pad to a fixed bucket set so the
  jitted step compiles once per bucket. TPU-native; the closest reference
  knob is cudnnAlgoMode's compile-once-per-shape algo selection.
- ``DL4J_TPU_COMPILE_CACHE`` — directory for the persistent on-disk XLA
  compilation cache (util/compile_cache.py): a restarted process
  deserializes executables instead of recompiling. Empty/unset = off.
- ``DL4J_TPU_TELEMETRY`` — unified telemetry registry (util/telemetry.py,
  docs/OBSERVABILITY.md): counters/gauges/histograms, cross-process trace
  spans, /metrics + /healthz on the UI server. Default ON (span cost is
  ~µs against ms-scale steps — bench.py ``telemetry_overhead``); set to
  0/false to strip every recording hook.
- ``DL4J_TPU_TRACE_SAMPLE`` — serving request-trace head-sampling keep
  fraction in [0, 1] (serving/scheduler.py,
  docs/OBSERVABILITY.md#request-tracing--slos): the fraction of healthy
  requests whose per-phase spans (queue wait / batch fill / compute /
  per-token decode) land on the merged trace. Slow, shed, and errored
  requests are ALWAYS kept regardless of the dice; ``0`` disables
  request tracing entirely (bench.py ``request_tracing_overhead``
  A/B's 1 vs 0). Unset = 0.02. The flight recorder is independent of
  this knob and always records.
- ``DL4J_TPU_FAULTS`` — chaos knob for the elastic runtime
  (util/faults.py, docs/FAULT_TOLERANCE.md): arm injectable faults as
  ``"kind[@step][:arg]"`` pairs, e.g.
  ``"kill_etl_worker,inject_nan@5,stall_prefetch:3.0"``. Kinds:
  ``kill_etl_worker`` (SIGKILL a transform worker), ``stall_prefetch``
  (wedge the producer thread), ``drop_heartbeat`` (membership sees this
  host die), ``inject_nan`` (poison one batch), ``sigkill_host`` (kill
  this process). Read once at first injector access; unknown kinds raise.
  Unset = no faults (the injector costs one dict lookup per seam).
- ``DL4J_TPU_PEAK_FLOPS`` — the accelerator's peak FLOP/s, either a bare
  number (``1.97e14``) or a per-dtype table (``bf16=1.97e14,fp32=9.85e13``
  — TPU peaks differ ~2x by dtype, so a bf16 run must not compute MFU
  against the fp32 roof). Enables MFU (model FLOPs utilization) in
  ``net.cost_report()`` (which looks up its conf's compute dtype in the
  table), the ``/costs`` route, and the
  ``train.model_flops_utilization`` telemetry gauge (util/cost_model.py,
  docs/OBSERVABILITY.md). Unset = throughput is still reported,
  utilization is not (no silent guesses about the hardware).
- ``DL4J_TPU_KERNEL_IMPL`` — default hot-path kernel dispatch for new
  configs and direct op calls ("auto" | "exact" | "pallas" —
  ops/kernels/, docs/KERNELS.md): ``auto`` engages the hand-tiled Pallas
  conv/LSTM kernels only on the TPU backend, ``exact`` pins the XLA-HLO
  reference path, ``pallas`` forces the kernels (Pallas interpreter on
  CPU — the correctness-test mode).
- ``DL4J_TPU_FUSED_UPDATE`` — default ``fused_update`` for new configs:
  the optimizer apply runs over dtype-grouped contiguous buffers in the
  donated train step instead of walking the param tree per leaf
  (docs/KERNELS.md#fused-optimizer-apply).
- ``DL4J_TPU_TUNING_DB`` — directory of the persistent autotuning
  database (tuning/database.py, docs/AUTOTUNE.md): measured winners keyed
  by (op, shape-signature, dtype, backend, topology), written by
  ``benchmarks/autotune.py`` sweeps and consulted at trace time by
  ``kernel_impl=auto`` dispatch (conv/LSTM impl + tile parameters) and by
  conf-time knob defaulting (an unset ``remat_policy`` takes the measured
  winner). Every entry is equivalence-gated before commit — the r6
  honesty convention made executable. Empty/unset = off (auto keeps its
  honest prior: compiled kernels only on the real chip).
- ``DL4J_TPU_PIPE_STAGES`` — default ``pipe_stages`` for new configs
  (parallel/pipelined.py, docs/DISTRIBUTED.md#pipeline-parallelism):
  partition the net into N pipeline stages at its ``stage_boundary()``
  markers and let ``PipelinedTrainer`` place the stacked stage params
  over the mesh 'pipe' axis — "model too big for one chip" as a config
  knob. 0/unset = off. Inert on single-device ``fit()``.
- ``DL4J_TPU_GRAD_COMPRESSION`` — default ``grad_compression`` for new
  configs ("none" | "threshold" | "bitmap" | "onebit" —
  parallel/compression.py, docs/DISTRIBUTED.md#gradient-compression):
  ParallelWrapper then runs the encoded gradient all-reduce — per-worker
  encode(grad + error-feedback residual), all-reduce of the quantized
  payload, dense decode before the update. The reference's
  EncodedGradientsAccumulator threshold/bitmap wire machinery, collapsed
  into the one jit-compiled GSPMD step.
"""

from __future__ import annotations

import os
from typing import Optional


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int, floor: int = 0) -> int:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        n = int(v.strip())
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}") from None
    if n < floor:
        raise ValueError(f"{name} must be >= {floor}, got {n}")
    return n


class Environment:
    """Mutable runtime-flag singleton (Nd4j.getEnvironment() parity)."""

    _instance: Optional["Environment"] = None

    def __init__(self):
        self.debug = _env_bool("DL4J_TPU_DEBUG")
        self.verbose = _env_bool("DL4J_TPU_VERBOSE")
        self.profiling = _env_bool("DL4J_TPU_PROFILING")
        self.nan_panic = _env_bool("DL4J_TPU_NAN_PANIC")
        self.default_compute_dtype = os.environ.get(
            "DL4J_TPU_COMPUTE_DTYPE", "float32")
        self.default_remat_policy = (
            os.environ.get("DL4J_TPU_REMAT_POLICY") or None)
        if self.default_remat_policy == "none":
            self.default_remat_policy = None
        self.default_sync_every = _env_int("DL4J_TPU_SYNC_EVERY", 1, floor=1)
        # hot-path kernel engine defaults (ops/kernels/, docs/KERNELS.md);
        # None = the ops-level resolver's own env/auto fallback applies
        self.default_kernel_impl = (
            os.environ.get("DL4J_TPU_KERNEL_IMPL") or None)
        self.default_fused_update = _env_bool("DL4J_TPU_FUSED_UPDATE")
        # encoded gradient collectives default (parallel/compression.py);
        # validated by the conf Builder so a typo fails at config build
        self.default_grad_compression = (
            os.environ.get("DL4J_TPU_GRAD_COMPRESSION") or None)
        # pipeline parallelism default (parallel/pipelined.py): stage
        # count for new configs; 0 = off
        self.default_pipe_stages = _env_int("DL4J_TPU_PIPE_STAGES", 0,
                                            floor=0)
        # autotuning database (tuning/database.py; the authoritative read
        # is database_dir() — surfaced here so crash dumps show the knob)
        self.tuning_db_dir = os.environ.get("DL4J_TPU_TUNING_DB") or None
        self.etl_workers = _env_int("DL4J_TPU_ETL_WORKERS", 0, floor=0)
        self.default_buckets = os.environ.get("DL4J_TPU_BUCKETS") or None
        self.compile_cache_dir = (
            os.environ.get("DL4J_TPU_COMPILE_CACHE") or None)
        self.telemetry = _env_bool("DL4J_TPU_TELEMETRY", default=True)
        # request-trace head-sampling keep fraction (authoritative parse is
        # serving.scheduler.trace_sample_rate — memoized per raw string;
        # surfaced here so crash dumps show the knob)
        self.trace_sample = os.environ.get("DL4J_TPU_TRACE_SAMPLE") or None
        # armed-faults spec (authoritative parse lives in util/faults.py's
        # injector; surfaced here so crash dumps show the chaos config)
        self.fault_spec = os.environ.get("DL4J_TPU_FAULTS") or None
        self._profiler = None
        self._compile_cache_applied = False

    @property
    def peak_flops(self):
        """DL4J_TPU_PEAK_FLOPS as FLOP/s (None when unset/unparsable).
        Read live — ONE parser, in util/cost_model.py, serves this property,
        cost_report(), and the MFU gauges; a typo degrades to "no MFU", it
        never crashes training startup for an observability-only knob."""
        from deeplearning4j_tpu.util.cost_model import peak_flops_from_env

        return peak_flops_from_env()

    @classmethod
    def get_instance(cls) -> "Environment":
        if cls._instance is None:
            cls._instance = Environment()
            cls._instance._apply()
        return cls._instance

    # -- setters mirroring Nd4j.getEnvironment().setDebug/setVerbose ---------
    def set_debug(self, v: bool) -> "Environment":
        self.debug = v
        return self._apply()

    def set_verbose(self, v: bool) -> "Environment":
        self.verbose = v
        return self._apply()

    def set_profiling(self, v: bool) -> "Environment":
        self.profiling = v
        return self._apply()

    def set_nan_panic(self, v: bool) -> "Environment":
        self.nan_panic = v
        return self._apply()

    def set_telemetry(self, v: bool) -> "Environment":
        self.telemetry = v
        return self._apply()

    def _apply(self) -> "Environment":
        """Install/remove the profiler hook + logger level to match flags."""
        import logging

        from deeplearning4j_tpu.util.profiler import OpProfiler

        # only drive the logger level while a verbosity flag is ON; never
        # clobber an application's own configuration otherwise
        logger = logging.getLogger("deeplearning4j_tpu")
        if self.verbose or self.debug:
            logger.setLevel(logging.DEBUG)
            self._set_logger_level = True
        elif getattr(self, "_set_logger_level", False):
            logger.setLevel(logging.NOTSET)
            self._set_logger_level = False

        # share the OpProfiler SINGLETON so flag-driven and user-driven
        # profiling never install competing exec_op hooks; only touch its
        # config while the FLAGS own the hook — a user-started profiler's
        # settings are never clobbered by unrelated setter calls
        # persistent compilation cache: wire jax_compilation_cache_dir once
        # (idempotent; later enable_persistent_cache() calls can re-point it)
        if self.compile_cache_dir and not self._compile_cache_applied:
            from deeplearning4j_tpu.util.compile_cache import (
                enable_persistent_cache)

            enable_persistent_cache(self.compile_cache_dir)
            self._compile_cache_applied = True

        # unified telemetry switch: the module reads DL4J_TPU_TELEMETRY
        # itself at singleton creation; the setter keeps them in sync at
        # runtime. Only push when THIS flag changed — an unrelated setter
        # (set_debug etc.) must not clobber a direct telemetry.set_enabled()
        if self.telemetry != getattr(self, "_telemetry_applied", None):
            from deeplearning4j_tpu.util import telemetry as _telemetry

            _telemetry.set_enabled(self.telemetry)
            self._telemetry_applied = self.telemetry

        want_hook = self.profiling or self.nan_panic or self.debug
        prof = OpProfiler.get_instance()
        if want_hook:
            prof.config.profile_ops = self.profiling or self.debug
            prof.config.check_for_nan = self.nan_panic
            prof.config.check_for_inf = self.nan_panic
            prof.start()
            self._profiler = prof
        elif self._profiler is not None:
            prof.stop()
            self._profiler = None
        return self

    def profiler(self):
        return self._profiler


def get_environment() -> Environment:
    """``Nd4j.getEnvironment()`` parity."""
    return Environment.get_instance()
