// Native image pipeline: threaded JPEG/PNG decode + bilinear resize feeding
// float32 NHWC batches.
//
// Reference parity: datavec-data-image NativeImageLoader.java (JavaCPP
// OpenCV decode straight into off-heap INDArray buffers) + the
// AsyncDataSetIterator prefetch thread — path-cite, mount empty this round.
// The TPU build decodes with the system libjpeg/libpng on C++ threads that
// never touch the Python GIL; the consumer copies ready images into one
// page-aligned batch buffer handed to jax.device_put.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>
#include <csetjmp>

namespace {

struct DecodedImage {
  std::vector<uint8_t> pixels;  // HWC uint8
  int w = 0, h = 0, c = 0;
};

// ---------------------------------------------------------------- JPEG

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

bool decode_jpeg(const uint8_t* buf, size_t len, int want_c, DecodedImage* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = want_c == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = cinfo.output_width;
  out->h = cinfo.output_height;
  out->c = cinfo.output_components;
  out->pixels.resize(size_t(out->w) * out->h * out->c);
  size_t stride = size_t(out->w) * out->c;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->pixels.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ----------------------------------------------------------------- PNG

bool decode_png(const uint8_t* buf, size_t len, int want_c, DecodedImage* out) {
  png_image image;
  memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, buf, len)) return false;
  image.format = want_c == 1 ? PNG_FORMAT_GRAY : PNG_FORMAT_RGB;
  out->w = image.width;
  out->h = image.height;
  out->c = want_c == 1 ? 1 : 3;
  out->pixels.resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, out->pixels.data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  return true;
}

bool decode_any(const uint8_t* buf, size_t len, int want_c, DecodedImage* out) {
  if (len > 3 && buf[0] == 0xFF && buf[1] == 0xD8)
    return decode_jpeg(buf, len, want_c, out);
  if (len > 8 && buf[0] == 0x89 && buf[1] == 'P' && buf[2] == 'N' && buf[3] == 'G')
    return decode_png(buf, len, want_c, out);
  return false;
}

// -------------------------------------------------------------- resize

// bilinear uint8 HWC → float32 HWC (align-corners=false, PIL-like sampling)
void resize_bilinear_f32(const DecodedImage& img, int oh, int ow, float* out) {
  const int c = img.c;
  const float sy = float(img.h) / oh;
  const float sx = float(img.w) / ow;
  for (int y = 0; y < oh; y++) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = (int)fy;
    if (fy < 0) { fy = 0; y0 = 0; }
    int y1 = y0 + 1 < img.h ? y0 + 1 : img.h - 1;
    float wy = fy - y0;
    for (int x = 0; x < ow; x++) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = (int)fx;
      if (fx < 0) { fx = 0; x0 = 0; }
      int x1 = x0 + 1 < img.w ? x0 + 1 : img.w - 1;
      float wx = fx - x0;
      const uint8_t* p00 = img.pixels.data() + (size_t(y0) * img.w + x0) * c;
      const uint8_t* p01 = img.pixels.data() + (size_t(y0) * img.w + x1) * c;
      const uint8_t* p10 = img.pixels.data() + (size_t(y1) * img.w + x0) * c;
      const uint8_t* p11 = img.pixels.data() + (size_t(y1) * img.w + x1) * c;
      float* o = out + (size_t(y) * ow + x) * c;
      for (int k = 0; k < c; k++) {
        float top = p00[k] + (p01[k] - p00[k]) * wx;
        float bot = p10[k] + (p11[k] - p10[k]) * wx;
        o[k] = top + (bot - top) * wy;
      }
    }
  }
}

struct ImgBatch {
  float* data;   // (H, W, C)
  int label;
  int idx;
  int status;    // 0 ok, -1 decode failure, -2 unreadable
};

struct ImgPipeline {
  std::vector<std::string> paths;
  std::vector<int> labels;
  int oh, ow, c;
  size_t capacity;
  std::deque<ImgBatch> ready;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<int> next_file{0};
  std::atomic<int> done_workers{0};
  std::atomic<bool> stop{false};
  int n_threads;
  std::vector<std::thread> workers;

  void worker() {
    for (;;) {
      int idx = next_file.fetch_add(1);
      if (idx >= (int)paths.size() || stop.load()) break;
      ImgBatch b{nullptr, labels[idx], idx, 0};
      std::ifstream f(paths[idx], std::ios::binary | std::ios::ate);
      if (!f) {
        b.status = -2;
      } else {
        size_t len = f.tellg();
        f.seekg(0);
        std::vector<uint8_t> buf(len);
        f.read(reinterpret_cast<char*>(buf.data()), len);
        DecodedImage img;
        if (!decode_any(buf.data(), len, c, &img) || img.c != c) {
          b.status = -1;
        } else {
          b.data = static_cast<float*>(
              malloc(sizeof(float) * size_t(oh) * ow * c));
          resize_bilinear_f32(img, oh, ow, b.data);
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] { return ready.size() < capacity || stop.load(); });
      if (stop.load()) {
        if (b.data) free(b.data);
        return;
      }
      ready.push_back(b);
      cv_pop.notify_one();
    }
    done_workers.fetch_add(1);
    cv_pop.notify_all();
  }
};

}  // namespace

extern "C" {

// Decode + resize ONE file → float32 HWC into caller buffer. 0 ok.
int image_decode_file(const char* path, int oh, int ow, int c, float* out) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return -2;
  size_t len = f.tellg();
  f.seekg(0);
  std::vector<uint8_t> buf(len);
  f.read(reinterpret_cast<char*>(buf.data()), len);
  DecodedImage img;
  if (!decode_any(buf.data(), len, c, &img) || img.c != c) return -1;
  resize_bilinear_f32(img, oh, ow, out);
  return 0;
}

void* img_pipe_create(const char** paths, const int* labels, int n,
                      int oh, int ow, int c, int n_threads, int capacity) {
  ImgPipeline* p = new ImgPipeline();
  for (int i = 0; i < n; i++) {
    p->paths.emplace_back(paths[i]);
    p->labels.push_back(labels ? labels[i] : -1);
  }
  p->oh = oh;
  p->ow = ow;
  p->c = c;
  p->capacity = capacity > 0 ? capacity : 8;
  p->n_threads = n_threads > 0 ? n_threads : 2;
  for (int t = 0; t < p->n_threads; t++)
    p->workers.emplace_back([p] { p->worker(); });
  return p;
}

// Copy up to max_n ready images into out (max_n, oh, ow, c) + labels/indices.
// → n copied (0 = exhausted); decode failures are SKIPPED and counted in
// *n_failed.
long img_pipe_next_batch(void* pipe, float* out, int* labels_out,
                         int* indices_out, long max_n, int* n_failed) {
  ImgPipeline* p = static_cast<ImgPipeline*>(pipe);
  long n = 0;
  *n_failed = 0;
  size_t img_floats = size_t(p->oh) * p->ow * p->c;
  while (n < max_n) {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_pop.wait(lk, [&] {
      return !p->ready.empty() || p->done_workers.load() == p->n_threads;
    });
    if (p->ready.empty()) break;  // exhausted
    ImgBatch b = p->ready.front();
    p->ready.pop_front();
    p->cv_push.notify_one();
    lk.unlock();
    if (b.status != 0) {
      (*n_failed)++;
      continue;
    }
    memcpy(out + n * img_floats, b.data, sizeof(float) * img_floats);
    if (labels_out) labels_out[n] = b.label;
    if (indices_out) indices_out[n] = b.idx;
    free(b.data);
    n++;
  }
  return n;
}

void img_pipe_destroy(void* pipe) {
  ImgPipeline* p = static_cast<ImgPipeline*>(pipe);
  p->stop.store(true);
  p->cv_push.notify_all();
  p->cv_pop.notify_all();
  for (auto& t : p->workers) t.join();
  for (auto& b : p->ready)
    if (b.data) free(b.data);
  delete p;
}

}  // extern "C"
