// Native runtime: host staging arena + threaded CSV parse + async prefetch.
//
// Reference parity: the reference keeps its data path and memory management
// native — DataVec record readers feed off-heap buffers (NativeImageLoader /
// RecordConverter), AsyncDataSetIterator prefetches on dedicated threads, and
// workspaces (libnd4j include/memory/Workspace.h, MemoryRegistrator.h —
// path-cite, mount empty this round) provide arena allocation outside the
// GC. The TPU compute path stays JAX/XLA; this module is the native runtime
// AROUND it: the ETL hot loop (file IO + float parsing, the classic host
// bottleneck that starves the accelerator) runs here on C++ threads that
// never touch the Python GIL, double-buffered into page-aligned host arenas
// ready for jax.device_put.
//
// Exposed as a flat C ABI (the reference's NativeOps.h style) consumed via
// ctypes — no pybind11 dependency.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Host staging arena (workspace parity): bump allocator over one aligned slab
// ---------------------------------------------------------------------------

struct Arena {
  uint8_t* base;
  size_t capacity;
  std::atomic<size_t> used;
};

void* arena_create(size_t bytes) {
  void* mem = nullptr;
  if (posix_memalign(&mem, 4096, bytes) != 0) return nullptr;  // page-aligned
  Arena* a = new Arena();
  a->base = static_cast<uint8_t*>(mem);
  a->capacity = bytes;
  a->used.store(0);
  return a;
}

void* arena_alloc(void* arena, size_t bytes, size_t align) {
  Arena* a = static_cast<Arena*>(arena);
  if (align == 0) align = 64;
  size_t cur, next;
  do {
    cur = a->used.load();
    size_t aligned = (cur + align - 1) & ~(align - 1);
    next = aligned + bytes;
    if (next > a->capacity) return nullptr;
  } while (!a->used.compare_exchange_weak(cur, next));
  size_t aligned = (next - bytes);
  return a->base + aligned;
}

void arena_reset(void* arena) { static_cast<Arena*>(arena)->used.store(0); }

size_t arena_used(void* arena) { return static_cast<Arena*>(arena)->used.load(); }

size_t arena_capacity(void* arena) { return static_cast<Arena*>(arena)->capacity; }

void arena_destroy(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  free(a->base);
  delete a;
}

// ---------------------------------------------------------------------------
// CSV parsing (CSVRecordReader hot loop, natively)
// ---------------------------------------------------------------------------

// count data rows (non-empty lines)
long csv_count_rows(const char* data, size_t len) {
  long rows = 0;
  bool in_line = false;
  for (size_t i = 0; i < len; i++) {
    if (data[i] == '\n') {
      if (in_line) rows++;
      in_line = false;
    } else if (data[i] != '\r') {
      in_line = true;
    }
  }
  if (in_line) rows++;
  return rows;
}

// parse up to max_rows lines of `cols` floats; returns rows parsed, -1 on
// malformed input (wrong column count)
long csv_parse(const char* data, size_t len, char delim, float* out,
               long max_rows, long cols) {
  long row = 0;
  size_t i = 0;
  while (i < len && row < max_rows) {
    // skip blank lines
    while (i < len && (data[i] == '\n' || data[i] == '\r')) i++;
    if (i >= len) break;
    long col = 0;
    while (i < len && data[i] != '\n') {
      char* end = nullptr;
      float v = strtof(data + i, &end);
      if (end == data + i) {  // not a number (e.g. quoted text) → NaN
        v = NAN;
        while (i < len && data[i] != delim && data[i] != '\n' &&
               data[i] != '\r')
          i++;
        end = const_cast<char*>(data + i);
      }
      if (col >= cols) return -1;
      out[row * cols + col] = v;
      col++;
      i = end - data;
      while (i < len && data[i] == ' ') i++;
      if (i < len && data[i] == delim) i++;
      while (i < len && data[i] == '\r') i++;
    }
    if (col != cols) return -1;
    row++;
    if (i < len) i++;  // consume '\n'
  }
  return row;
}

// ---------------------------------------------------------------------------
// Async file pipeline (AsyncDataSetIterator parity): worker threads read +
// parse whole files, bounded ring hands them to the consumer
// ---------------------------------------------------------------------------

struct Batch {
  float* data;
  long rows;
  int file_idx;
};

struct Pipeline {
  std::vector<std::string> paths;
  int cols;
  char delim;
  size_t capacity;
  std::deque<Batch> ready;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<int> next_file{0};
  std::atomic<int> done_workers{0};
  std::atomic<bool> stop{false};
  int n_threads;
  std::vector<std::thread> workers;
  // files must be delivered in order (determinism parity with the
  // single-threaded reader): workers park finished files until their turn
  std::atomic<int> next_emit{0};
  std::deque<Batch> parked;

  void worker() {
    for (;;) {
      int idx = next_file.fetch_add(1);
      if (idx >= static_cast<int>(paths.size()) || stop.load()) break;
      std::ifstream f(paths[idx], std::ios::binary | std::ios::ate);
      Batch b{nullptr, 0, idx};
      if (f) {
        size_t len = f.tellg();
        f.seekg(0);
        std::vector<char> buf(len);
        f.read(buf.data(), len);
        long rows = csv_count_rows(buf.data(), len);
        float* out = static_cast<float*>(malloc(sizeof(float) * rows * cols));
        long parsed = csv_parse(buf.data(), len, delim, out, rows, cols);
        if (parsed < 0) {
          free(out);
          b.rows = -1;  // malformed marker
        } else {
          b.data = out;
          b.rows = parsed;
        }
      } else {
        b.rows = -2;  // unreadable marker
      }
      std::unique_lock<std::mutex> lk(mu);
      parked.push_back(b);
      // drain in-order parked batches into the ready queue. NOTE: cv waits
      // release the lock, so other workers may erase from `parked` and
      // advance next_emit meanwhile — iterators must be RE-FOUND after every
      // wait, never held across one (TSan-caught use-after-free otherwise).
      for (;;) {
        auto find_next = [&] {
          for (auto it = parked.begin(); it != parked.end(); ++it)
            if (it->file_idx == next_emit.load()) return it;
          return parked.end();
        };
        if (find_next() == parked.end()) break;
        cv_push.wait(lk, [&] {
          return ready.size() < capacity || stop.load();
        });
        if (stop.load()) return;
        auto it = find_next();  // re-find: state may have changed in the wait
        if (it == parked.end()) break;
        ready.push_back(*it);
        parked.erase(it);
        next_emit.fetch_add(1);
        cv_pop.notify_one();
      }
    }
    done_workers.fetch_add(1);
    cv_pop.notify_all();
  }
};

void* pipe_create(const char** paths, int n_paths, int cols, char delim,
                  int n_threads, int capacity) {
  Pipeline* p = new Pipeline();
  for (int i = 0; i < n_paths; i++) p->paths.emplace_back(paths[i]);
  p->cols = cols;
  p->delim = delim;
  p->capacity = capacity > 0 ? capacity : 4;
  p->n_threads = n_threads > 0 ? n_threads : 2;
  for (int t = 0; t < p->n_threads; t++)
    p->workers.emplace_back([p] { p->worker(); });
  return p;
}

// → rows (>=0), or -1 malformed file, -2 unreadable file, -3 exhausted
long pipe_next(void* pipe, float** out_data, int* out_file_idx) {
  Pipeline* p = static_cast<Pipeline*>(pipe);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_pop.wait(lk, [&] {
    return !p->ready.empty() || p->done_workers.load() == p->n_threads;
  });
  if (p->ready.empty()) return -3;
  Batch b = p->ready.front();
  p->ready.pop_front();
  p->cv_push.notify_one();
  *out_data = b.data;
  *out_file_idx = b.file_idx;
  return b.rows;
}

void pipe_free_batch(float* data) { free(data); }

void pipe_destroy(void* pipe) {
  Pipeline* p = static_cast<Pipeline*>(pipe);
  p->stop.store(true);
  p->cv_push.notify_all();
  p->cv_pop.notify_all();
  for (auto& t : p->workers) t.join();
  for (auto& b : p->ready)
    if (b.data) free(b.data);
  for (auto& b : p->parked)
    if (b.data) free(b.data);
  delete p;
}

}  // extern "C"
