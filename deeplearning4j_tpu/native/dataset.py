"""NativeCSVDataSetIterator: the native pipeline as a DataSetIterator.

Reference parity: RecordReaderDataSetIterator wrapped in
AsyncDataSetIterator (SURVEY.md §3.1: "async-prefetch wrapper ... separate
thread") — here the prefetch thread pool, file IO, and float parsing are all
native (csrc/dl4jtpu_native.cpp); Python only slices batches and one-hots
labels.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu import native
from deeplearning4j_tpu.data.dataset import DataSet


class NativeCSVDataSetIterator:
    """Iterate DataSet minibatches over many CSV shards.

    ``label_index`` column becomes the label (one-hot with ``num_classes``,
    raw for regression); remaining columns are features."""

    def __init__(self, paths: List[str], batch_size: int, n_columns: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False, delimiter: str = ",",
                 n_threads: int = 2, prefetch: int = 4):
        if not native.is_available():
            raise RuntimeError(f"native build unavailable: {native.build_error()}")
        self.paths = list(paths)
        self.batch_size = batch_size
        self.n_columns = n_columns
        self.label_index = label_index % n_columns
        self.num_classes = num_classes
        self.regression = regression
        self.delimiter = delimiter
        self.n_threads = n_threads
        self.prefetch = prefetch

    def reset(self):
        pass  # a fresh pipeline is created per epoch in __iter__

    def _emit(self, rows: np.ndarray) -> DataSet:
        li = self.label_index
        labels = rows[:, li]
        feats = np.delete(rows, li, axis=1)
        if self.regression:
            y = labels[:, None].astype(np.float32)
        else:
            y = np.eye(self.num_classes, dtype=np.float32)[
                labels.astype(np.int64)]
        return DataSet(feats, y)

    def __iter__(self):
        pipe = native.AsyncCSVPipeline(
            self.paths, cols=self.n_columns, delimiter=self.delimiter,
            n_threads=self.n_threads, prefetch=self.prefetch)
        try:
            pending: Optional[np.ndarray] = None
            for _, arr in pipe:
                buf = arr if pending is None else np.concatenate([pending, arr])
                n_full = len(buf) // self.batch_size * self.batch_size
                for s in range(0, n_full, self.batch_size):
                    yield self._emit(buf[s:s + self.batch_size])
                pending = buf[n_full:] if n_full < len(buf) else None
            if pending is not None and len(pending):
                yield self._emit(pending)
        finally:
            pipe.close()
