"""Native runtime bindings: host arena + async CSV pipeline.

Reference parity: the flat C ABI mirrors NativeOps.h/JavaCPP (SURVEY.md §2.1
N8) — here compiled from ``csrc/dl4jtpu_native.cpp`` with the system g++ on
first use and bound via ctypes (no pybind11 in the image). Everything is
gated behind :func:`is_available`; pure-Python fallbacks exist throughout the
framework, so the native path is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csrc", "dl4jtpu_native.cpp")
_SRC_IMG = os.path.join(_HERE, "csrc", "dl4jtpu_image.cpp")
_SO = os.path.join(_HERE, "_dl4jtpu_native.so")

_lib = None
_lock = threading.Lock()
_build_error: Optional[str] = None
_image_supported = False


def _build() -> Optional[str]:
    """Compile the native library if missing/stale. → error message or None."""
    global _image_supported
    try:
        srcs = [_SRC, _SRC_IMG]
        if (os.path.exists(_SO)
                and all(os.path.getmtime(_SO) >= os.path.getmtime(s)
                        for s in srcs)):
            _image_supported = True
            return None
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               *srcs, "-o", _SO + ".tmp", "-ljpeg", "-lpng"]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            # image decode libs may be absent: fall back to the CSV-only core
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", _SO + ".tmp"]
            proc2 = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=300)
            if proc2.returncode != 0:
                return proc.stderr[-2000:]
        else:
            _image_supported = True
        os.replace(_SO + ".tmp", _SO)
        return None
    except Exception as e:  # no compiler, read-only fs, ...
        return repr(e)


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        lib = ctypes.CDLL(_SO)
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_create.argtypes = [ctypes.c_size_t]
        lib.arena_alloc.restype = ctypes.c_void_p
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t]
        lib.arena_reset.argtypes = [ctypes.c_void_p]
        lib.arena_used.restype = ctypes.c_size_t
        lib.arena_used.argtypes = [ctypes.c_void_p]
        lib.arena_capacity.restype = ctypes.c_size_t
        lib.arena_capacity.argtypes = [ctypes.c_void_p]
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        lib.csv_count_rows.restype = ctypes.c_long
        lib.csv_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.csv_parse.restype = ctypes.c_long
        lib.csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char,
                                  ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                                  ctypes.c_long]
        lib.pipe_create.restype = ctypes.c_void_p
        lib.pipe_create.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                                    ctypes.c_int, ctypes.c_char, ctypes.c_int,
                                    ctypes.c_int]
        lib.pipe_next.restype = ctypes.c_long
        lib.pipe_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                                  ctypes.POINTER(ctypes.c_int)]
        lib.pipe_free_batch.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.pipe_destroy.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "image_decode_file"):
            lib.image_decode_file.restype = ctypes.c_int
            lib.image_decode_file.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float)]
            lib.img_pipe_create.restype = ctypes.c_void_p
            lib.img_pipe_create.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int]
            lib.img_pipe_next_batch.restype = ctypes.c_long
            lib.img_pipe_next_batch.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.c_long, ctypes.POINTER(ctypes.c_int)]
            lib.img_pipe_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


# ---------------------------------------------------------------------------
# Host arena (workspace parity)
# ---------------------------------------------------------------------------


class HostArena:
    """Page-aligned bump allocator for staging buffers (MemoryWorkspace
    parity — scoped use: allocate per step, reset after device_put)."""

    def __init__(self, capacity_bytes: int):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self._ptr = lib.arena_create(capacity_bytes)
        if not self._ptr:
            raise MemoryError("arena_create failed")

    def alloc_array(self, shape, dtype=np.float32, align: int = 64) -> np.ndarray:
        """A numpy view over arena memory (no copy on reset — reuse)."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        p = self._lib.arena_alloc(self._ptr, nbytes, align)
        if not p:
            raise MemoryError("arena exhausted")
        buf = (ctypes.c_char * nbytes).from_address(p)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def used(self) -> int:
        return self._lib.arena_used(self._ptr)

    def capacity(self) -> int:
        return self._lib.arena_capacity(self._ptr)

    def reset(self):
        """Invalidates previously returned views — scope discipline is the
        caller's (the reference throws on workspace scope violations)."""
        self._lib.arena_reset(self._ptr)

    def close(self):
        if self._ptr:
            self._lib.arena_destroy(self._ptr)
            self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------


def parse_csv(text: bytes, cols: int, delimiter: str = ",") -> np.ndarray:
    """Parse CSV bytes → (rows, cols) float32. Non-numeric cells → NaN."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    if isinstance(text, str):
        text = text.encode()
    rows = lib.csv_count_rows(text, len(text))
    out = np.empty((rows, cols), np.float32)
    parsed = lib.csv_parse(
        text, len(text), delimiter.encode()[0:1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), rows, cols)
    if parsed < 0:
        raise ValueError("malformed CSV (inconsistent column count)")
    return out[:parsed]


class AsyncCSVPipeline:
    """Threaded read+parse of many CSV files, delivered in order
    (AsyncDataSetIterator parity: bounded prefetch off the training thread).

    Iterate → (file_index, float32 array (rows, cols))."""

    def __init__(self, paths: List[str], cols: int, delimiter: str = ",",
                 n_threads: int = 2, prefetch: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self.paths = [os.fspath(p) for p in paths]
        self.cols = cols
        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths])
        self._keepalive = arr
        self._ptr = lib.pipe_create(arr, len(self.paths), cols,
                                    delimiter.encode()[0:1], n_threads, prefetch)

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[int, np.ndarray]:
        data = ctypes.POINTER(ctypes.c_float)()
        idx = ctypes.c_int()
        rows = self._lib.pipe_next(self._ptr, ctypes.byref(data),
                                   ctypes.byref(idx))
        if rows == -3:
            raise StopIteration
        if rows == -1:
            raise ValueError(f"malformed CSV: {self.paths[idx.value]}")
        if rows == -2:
            raise IOError(f"unreadable file: {self.paths[idx.value]}")
        try:
            arr = np.ctypeslib.as_array(data, shape=(rows, self.cols)).copy()
        finally:
            self._lib.pipe_free_batch(data)
        return idx.value, arr

    def close(self):
        if getattr(self, "_ptr", None):
            self._lib.pipe_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        self.close()


# ---------------------------------------------------------------------------
# Image pipeline (NativeImageLoader parity)
# ---------------------------------------------------------------------------


def image_available() -> bool:
    """True when the native image decode path (libjpeg/libpng) compiled in."""
    return _load() is not None and hasattr(_lib, "image_decode_file")


def decode_image_file(path: str, height: int, width: int,
                      channels: int = 3) -> np.ndarray:
    """Decode JPEG/PNG + bilinear resize → float32 (H, W, C) in [0, 255]."""
    lib = _load()
    if lib is None or not hasattr(lib, "image_decode_file"):
        raise RuntimeError(f"native image decode unavailable: {_build_error}")
    out = np.empty((height, width, channels), np.float32)
    rc = lib.image_decode_file(
        os.fspath(path).encode(), height, width, channels,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if rc == -2:
        raise IOError(f"unreadable image: {path}")
    if rc != 0:
        raise ValueError(f"undecodable image (JPEG/PNG only): {path}")
    return out


class AsyncImagePipeline:
    """Threaded decode+resize of many images into float32 NHWC batches
    (NativeImageLoader + AsyncDataSetIterator parity: the ETL hot path the
    reference keeps native so the accelerator is never input-bound).

    Iterate → (x (n, H, W, C) float32, labels (n,) int32, indices (n,) int32);
    undecodable files are skipped (counted in .failed)."""

    def __init__(self, paths, labels=None, height=224, width=224, channels=3,
                 batch=32, n_threads: int = 4, prefetch: int = 64):
        lib = _load()
        if lib is None or not hasattr(lib, "img_pipe_create"):
            raise RuntimeError(
                f"native image pipeline unavailable: {_build_error}")
        self._lib = lib
        self.paths = [os.fspath(p) for p in paths]
        self.height, self.width, self.channels = height, width, channels
        self.batch = batch
        self.failed = 0
        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths])
        labs = (ctypes.c_int * len(self.paths))(
            *([int(l) for l in labels] if labels is not None
              else [-1] * len(self.paths)))
        self._keepalive = (arr, labs)
        self._ptr = lib.img_pipe_create(arr, labs, len(self.paths),
                                        height, width, channels,
                                        n_threads, prefetch)

    def __iter__(self):
        return self

    def __next__(self):
        x = np.empty((self.batch, self.height, self.width, self.channels),
                     np.float32)
        labels = np.empty((self.batch,), np.int32)
        indices = np.empty((self.batch,), np.int32)
        n_failed = ctypes.c_int()
        n = self._lib.img_pipe_next_batch(
            self._ptr, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            self.batch, ctypes.byref(n_failed))
        self.failed += n_failed.value
        if n == 0:
            raise StopIteration
        return x[:n], labels[:n], indices[:n]

    def close(self):
        if getattr(self, "_ptr", None):
            self._lib.img_pipe_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        self.close()
