"""Early stopping — org/deeplearning4j/earlystopping/** parity.

Reference components (path-cite, mount empty this round):
``EarlyStoppingConfiguration`` builder, epoch termination conditions
(``MaxEpochsTerminationCondition``, ``ScoreImprovementEpochTerminationCondition``),
iteration termination conditions (``MaxTimeIterationTerminationCondition``,
``MaxScoreIterationTerminationCondition``, ``InvalidScoreIterationTerminationCondition``),
``ScoreCalculator`` (``DataSetLossCalculator``), model savers
(``InMemoryModelSaver``, ``LocalFileModelSaver``), ``EarlyStoppingTrainer``
returning an ``EarlyStoppingResult`` with a ``TerminationReason``.

The training loop itself is the jitted whole-step program from
MultiLayerNetwork/ComputationGraph — early stopping is host-side control
around it (scores are the only device→host traffic).
"""

from __future__ import annotations

import copy
import math
import os
import time

import jax
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, List, Optional


# ----------------------------------------------------------------- conditions
class EpochTerminationCondition:
    requires_score = False  # skip on epochs with no validation score

    def initialize(self): ...
    def terminate(self, epoch: int, score: float) -> bool: ...


class IterationTerminationCondition:
    def initialize(self): ...
    def terminate(self, score: float) -> bool: ...


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``max_epochs_without_improvement`` epochs with < min_improvement."""

    requires_score = True

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement

    def initialize(self):
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch, score):
        if self.best - score >= self.min_improvement:
            self.best = score
            self.since = 0
        else:
            self.since += 1
        return self.since > self.patience


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds

    def initialize(self):
        self.start = time.monotonic()

    def terminate(self, score):
        return time.monotonic() - self.start > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, score):
        return math.isnan(score) or math.isinf(score)


# ------------------------------------------------------------------- scoring
class ScoreCalculator:
    def calculate_score(self, model) -> float: ...


class DataSetLossCalculator(ScoreCalculator):
    """Mean loss over a held-out iterator (DataSetLossCalculator parity)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total, n = 0.0, 0
        for ds in self.iterator:
            b = ds.features.shape[0] if hasattr(ds.features, "shape") else len(ds.features)
            total += model.score(ds) * b
            n += b
        return total / n if self.average and n else total


# -------------------------------------------------------------------- savers
def _host_snapshot(model):
    """Shallow-copy the model with params/states/opt_states pulled to host
    numpy. The jitted train step donates the device buffers
    (donate_argnums=(0,1,2) in MultiLayerNetwork._build_train_step), so a
    plain reference-sharing copy would hold deleted arrays after the next
    iteration on TPU; host copies are immune."""
    import numpy as np

    snap = copy.copy(model)
    to_host = lambda t: jax.tree_util.tree_map(lambda x: np.asarray(x), t)
    snap.params = to_host(model.params)
    snap.states = to_host(model.states)
    snap.opt_states = to_host(model.opt_states)
    snap.listeners = []  # don't carry live listeners (e.g. the trainer's guard)
    return snap


class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self.latest = None

    def save_best_model(self, model, score):
        self.best = _host_snapshot(model)

    def save_latest_model(self, model, score):
        self.latest = _host_snapshot(model)

    def get_best_model(self):
        return self.best


class LocalFileModelSaver:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score):
        from deeplearning4j_tpu.util import ModelSerializer

        ModelSerializer.write_model(model, self._path("bestModel.zip"))

    def save_latest_model(self, model, score):
        from deeplearning4j_tpu.util import ModelSerializer

        ModelSerializer.write_model(model, self._path("latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.util import ModelSerializer

        path = self._path("bestModel.zip")
        if not os.path.exists(path):
            # training terminated before any best model was saved (e.g. NaN
            # termination in epoch 1) — match InMemoryModelSaver: return None
            # so the EarlyStoppingResult still carries the termination reason
            return None
        return ModelSerializer.restore_model(path)


# --------------------------------------------------------------------- config
@dataclass
class EarlyStoppingConfiguration:
    score_calculator: ScoreCalculator
    model_saver: Any = field(default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List[EpochTerminationCondition] = field(
        default_factory=list
    )
    iteration_termination_conditions: List[IterationTerminationCondition] = field(
        default_factory=list
    )
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    class Builder:
        def __init__(self):
            self._score_calc = None
            self._saver = None
            self._epoch_conds = []
            self._iter_conds = []
            self._every_n = 1
            self._save_last = False

        def score_calculator(self, sc):
            self._score_calc = sc
            return self

        def model_saver(self, s):
            self._saver = s
            return self

        def epoch_termination_conditions(self, *conds):
            self._epoch_conds.extend(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._iter_conds.extend(conds)
            return self

        def evaluate_every_n_epochs(self, n):
            self._every_n = n
            return self

        def save_last_model(self, b=True):
            self._save_last = b
            return self

        def build(self):
            return EarlyStoppingConfiguration(
                score_calculator=self._score_calc,
                model_saver=self._saver or InMemoryModelSaver(),
                epoch_termination_conditions=self._epoch_conds,
                iteration_termination_conditions=self._iter_conds,
                evaluate_every_n_epochs=self._every_n,
                save_last_model=self._save_last,
            )

    @staticmethod
    def builder():
        return EarlyStoppingConfiguration.Builder()


class TerminationReason(Enum):
    Error = "Error"
    IterationTerminationCondition = "IterationTerminationCondition"
    EpochTerminationCondition = "EpochTerminationCondition"


@dataclass
class EarlyStoppingResult:
    termination_reason: TerminationReason
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any


# -------------------------------------------------------------------- trainer
class EarlyStoppingTrainer:
    """EarlyStoppingTrainer / EarlyStoppingGraphTrainer parity — drives
    net.fit one epoch at a time, scoring and checking conditions between."""

    def __init__(self, config: EarlyStoppingConfiguration, network, train_iterator):
        self.config = config
        self.net = network
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()

        best_score, best_epoch = math.inf, -1
        scores: dict = {}
        epoch = 0
        reason, details = TerminationReason.EpochTerminationCondition, "max loop"

        class _IterGuard:
            """Listener checking iteration conditions during the epoch."""

            def __init__(self):
                self.tripped: Optional[str] = None

            def iteration_done(self, model, iteration, ep):
                if self.tripped:
                    return
                score = model.get_score()
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(score):
                        self.tripped = type(c).__name__
                        raise _IterStop(self.tripped)

            def on_epoch_end(self, model):
                pass

        class _IterStop(Exception):
            pass

        saved_listeners = list(getattr(self.net, "listeners", []))
        if cfg.iteration_termination_conditions:
            # only install the guard when needed — get_score() forces a
            # device→host sync per iteration
            self.net.set_listeners(*saved_listeners, _IterGuard())
        try:
            while True:
                try:
                    if hasattr(self.iterator, "reset"):
                        self.iterator.reset()
                    self.net.fit(self.iterator, epochs=1)
                except _IterStop as e:
                    reason = TerminationReason.IterationTerminationCondition
                    details = str(e)
                    break
                epoch += 1
                if epoch % cfg.evaluate_every_n_epochs == 0:
                    score = cfg.score_calculator.calculate_score(self.net)
                    scores[epoch] = score
                    if score < best_score:
                        best_score, best_epoch = score, epoch
                        cfg.model_saver.save_best_model(self.net, score)
                if cfg.save_last_model:  # every epoch, eval or not
                    cfg.model_saver.save_latest_model(self.net, scores.get(epoch))
                stop = False
                for c in cfg.epoch_termination_conditions:
                    if c.requires_score and epoch not in scores:
                        continue  # no validation ran this epoch
                    if c.terminate(epoch, scores.get(epoch, math.inf)):
                        reason = TerminationReason.EpochTerminationCondition
                        details = type(c).__name__
                        stop = True
                        break
                if stop:
                    break
        finally:
            self.net.set_listeners(*saved_listeners)

        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            score_vs_epoch=scores,
            best_model=cfg.model_saver.get_best_model(),
        )


EarlyStoppingGraphTrainer = EarlyStoppingTrainer
