"""Arbiter: hyperparameter optimization.

Reference parity: the ``arbiter/`` module (SURVEY.md §2.2 J21) —
ParameterSpace implementations (ContinuousParameterSpace,
IntegerParameterSpace, DiscreteParameterSpace), candidate generators
(RandomSearchGenerator, GridSearchCandidateGenerator), and the
OptimizationRunner with score functions + termination conditions —
path-cite, mount empty this round.

API:

    space = {"lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True),
             "hidden": IntegerParameterSpace(8, 64),
             "act": DiscreteParameterSpace("relu", "tanh")}
    runner = OptimizationRunner(
        space, RandomSearchGenerator(16, seed=0),
        model_builder=lambda cfg: build_net(cfg),
        score_fn=lambda net: net.score(x=xv, y=yv),
        minimize=True)
    result = runner.execute()
    result.best_candidate, result.best_score, result.results
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng) -> Any:
        raise NotImplementedError

    def grid(self, n: int) -> List[Any]:
        raise NotImplementedError


@dataclasses.dataclass
class ContinuousParameterSpace(ParameterSpace):
    low: float
    high: float
    log_scale: bool = False

    def sample(self, rng):
        if self.log_scale:
            return float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))
        return float(rng.uniform(self.low, self.high))

    def grid(self, n):
        if self.log_scale:
            return list(np.exp(np.linspace(np.log(self.low), np.log(self.high), n)))
        return list(np.linspace(self.low, self.high, n))


@dataclasses.dataclass
class IntegerParameterSpace(ParameterSpace):
    low: int
    high: int  # inclusive

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid(self, n):
        return sorted({int(round(v)) for v in
                       np.linspace(self.low, self.high, min(n, self.high - self.low + 1))})


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self, n):
        return list(self.values)


@dataclasses.dataclass
class FixedValue(ParameterSpace):
    value: Any

    def sample(self, rng):
        return self.value

    def grid(self, n):
        return [self.value]


class RandomSearchGenerator:
    """RandomSearchGenerator parity: n i.i.d. samples from the space."""

    def __init__(self, num_candidates: int, seed: int = 0):
        self.num_candidates = num_candidates
        self.seed = seed

    def candidates(self, space: Dict[str, ParameterSpace]):
        rng = np.random.default_rng(self.seed)
        for _ in range(self.num_candidates):
            yield {k: s.sample(rng) for k, s in space.items()}


class GridSearchCandidateGenerator:
    """GridSearchCandidateGenerator parity: cartesian product of per-space
    discretizations (``discretization_count`` points for continuous)."""

    def __init__(self, discretization_count: int = 5):
        self.discretization_count = discretization_count

    def candidates(self, space: Dict[str, ParameterSpace]):
        keys = list(space)
        axes = [space[k].grid(self.discretization_count) for k in keys]
        for combo in itertools.product(*axes):
            yield dict(zip(keys, combo))


class GeneticSearchCandidateGenerator:
    """GeneticSearchCandidateGenerator parity (arbiter-core
    generator/GeneticSearchCandidateGenerator.java + genetic/* — path-cite,
    mount empty): population-based search with tournament selection,
    uniform crossover, and per-gene mutation. The runner feeds scores back
    through ``report`` (the reference's PopulationModel listener role);
    each generation after the first is bred from the best of the last."""

    def __init__(self, population_size: int = 8, generations: int = 5,
                 tournament_k: int = 3, mutation_rate: float = 0.15,
                 minimize: bool = True, seed: int = 0):
        self.population_size = population_size
        self.generations = generations
        self.tournament_k = tournament_k
        self.mutation_rate = mutation_rate
        self.minimize = minimize
        self.seed = seed
        self._scored: List["CandidateResult"] = []

    def report(self, result: "CandidateResult"):
        self._scored.append(result)

    def _select(self, rng, pop_scores):
        """Tournament selection over (candidate, score) pairs."""
        picks = [pop_scores[int(rng.integers(0, len(pop_scores)))]
                 for _ in range(self.tournament_k)]
        key = (min if self.minimize else max)
        return key(picks, key=lambda cs: cs[1])[0]

    def _breed(self, rng, a, b, space):
        child = {}
        for k in space:
            child[k] = a[k] if rng.random() < 0.5 else b[k]  # uniform xover
            if rng.random() < self.mutation_rate:
                child[k] = space[k].sample(rng)
        return child

    def candidates(self, space: Dict[str, "ParameterSpace"]):
        rng = np.random.default_rng(self.seed)
        population = [{k: s.sample(rng) for k, s in space.items()}
                      for _ in range(self.population_size)]
        for gen in range(self.generations):
            mark = len(self._scored)
            for cand in population:
                yield dict(cand)
            scored = [(r.candidate, r.score) for r in self._scored[mark:]
                      if not math.isnan(r.score)]
            if not scored:  # every candidate failed: fresh random restart
                population = [{k: s.sample(rng) for k, s in space.items()}
                              for _ in range(self.population_size)]
                continue
            # elitism: carry the generation's best through unchanged
            key = (min if self.minimize else max)
            elite = key(scored, key=lambda cs: cs[1])[0]
            population = [dict(elite)] + [
                self._breed(rng, self._select(rng, scored),
                            self._select(rng, scored), space)
                for _ in range(self.population_size - 1)]


@dataclasses.dataclass
class CandidateResult:
    candidate: Dict[str, Any]
    score: float
    duration_s: float
    index: int
    error: Optional[str] = None


@dataclasses.dataclass
class OptimizationResult:
    best_candidate: Optional[Dict[str, Any]]
    best_score: float
    best_model: Any
    results: List[CandidateResult]


class MaxCandidatesCondition:
    def __init__(self, n):
        self.n = n

    def done(self, n_done, elapsed):
        return n_done >= self.n


class MaxTimeCondition:
    def __init__(self, seconds):
        self.seconds = seconds

    def done(self, n_done, elapsed):
        return elapsed >= self.seconds


class OptimizationRunner:
    """LocalOptimizationRunner parity: evaluate candidates sequentially (the
    reference parallelizes over executors; on one host the accelerator is the
    bottleneck and sequential keeps it saturated)."""

    def __init__(self, space: Dict[str, ParameterSpace], generator,
                 model_builder: Callable[[Dict[str, Any]], Any],
                 score_fn: Callable[[Any], float], minimize: bool = True,
                 termination_conditions: Sequence = ()):
        self.space = space
        self.generator = generator
        self.model_builder = model_builder
        self.score_fn = score_fn
        self.minimize = minimize
        self.termination_conditions = list(termination_conditions)

    def execute(self) -> OptimizationResult:
        results: List[CandidateResult] = []
        best: Optional[CandidateResult] = None
        best_model = None
        t_start = time.monotonic()
        for i, cand in enumerate(self.generator.candidates(self.space)):
            elapsed = time.monotonic() - t_start
            if any(c.done(len(results), elapsed) for c in self.termination_conditions):
                break
            t0 = time.monotonic()
            try:
                model = self.model_builder(cand)
                score = float(self.score_fn(model))
                cr = CandidateResult(cand, score, time.monotonic() - t0, i)
            except Exception as e:  # failed candidates recorded, not fatal
                cr = CandidateResult(cand, math.nan, time.monotonic() - t0, i,
                                     error=repr(e))
                model = None
            results.append(cr)
            if hasattr(self.generator, "report"):
                self.generator.report(cr)  # genetic search breeds on scores
            if not math.isnan(cr.score) and (
                best is None
                or (self.minimize and cr.score < best.score)
                or (not self.minimize and cr.score > best.score)
            ):
                best = cr
                best_model = model
        return OptimizationResult(
            best_candidate=best.candidate if best else None,
            best_score=best.score if best else math.nan,
            best_model=best_model,
            results=results,
        )
