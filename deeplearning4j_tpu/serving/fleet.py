"""Serving fleet: a front-tier HTTP router over N worker ModelServer
processes (docs/SERVING.md#fleet).

The serving tier below this module is deep — paged KV with shared-prefix
radix reuse, speculative decode, breakers, SLO brownout — but it lives in
ONE Python process behind one GIL and one accept loop. The fleet is the
horizontal half of the "millions of users" north star: a
:class:`FleetRouter` process spawns (or adopts) N worker processes, each
running the full single-process stack behind its own ``ModelServer``, and
proxies ``/v1/models/...`` traffic to them over persistent HTTP/1.1
connections. Each worker owns its own GIL, scheduler, and KV block pool,
so fleet QPS scales near-linearly in workers on a multi-core host.

Routing (``docs/SERVING.md#fleet``):

- **Prefix affinity** — generate requests hash the tokenized prompt HEAD
  (first ``affinity_head`` tokens, a declared ``tuning/`` dimension) with
  rendezvous/HRW hashing over the live ring, so streams sharing a system
  prompt land on the worker that already holds those radix-cache blocks.
  Rendezvous gives the two properties that matter here: deterministic,
  coordination-free placement (every router instance agrees), and minimal
  movement — when a worker leaves the ring, ONLY its keys move.
- **Least-loaded fallback** — requests with no prompt (classify) and
  affinity picks whose worker is already ``overflow_depth`` deep while a
  peer is strictly shallower go to the least-loaded ring member (rotating
  tiebreak), so one hot prefix cannot starve a worker.
- **Failover** — a connection-level proxy failure (refused/reset; never
  an HTTP error, those relay verbatim) retries the request on another
  live worker. Requests here are stateless-at-the-router, so a retry is
  safe; exhausting every worker answers 502, an empty ring answers 503 +
  ``Retry-After``.

Every decision increments
``serving.fleet.routing_decisions_total{reason=affinity|least_loaded|failover}``.

Health is woven into routing: a poller thread reads each worker's
``/healthz`` (breaker/SLO/drain state folded in by the worker itself) and
``/v1/models`` (queue depth, versions, prefix-cache hit rate); an
unhealthy or draining worker drops out of the ring without dropping the
fleet. A dead worker process (SIGKILL, OOM) is respawned by the
supervisor under :data:`~deeplearning4j_tpu.serving.resilience.
FLEET_RESPAWN_POLICY` backoff, re-warmed (the AOT export store makes that
cheap when ``export_dir`` rides in the spec), and re-enters the ring when
its ``/healthz`` goes green.

Rolling reload: ``POST /v1/models/<id>/reload`` against the router fans
out worker-by-worker, waiting for each worker's canary-validated swap
(the r18 zero-shed contract) before touching the next — the rest of the
ring keeps serving, versions advance monotonically, and the spawn spec is
rewritten so a later respawn loads the NEW weights.

    from deeplearning4j_tpu.serving.fleet import FleetRouter, fleet_spec

    spec = fleet_spec(models=[{"id": "lenet", "path": "lenet.zip",
                               "kind": "classify"}])
    fleet = FleetRouter(spec, n_workers=4).start()
    ...                                    # http://host:port/v1/models/...
    fleet.stop()
"""

from __future__ import annotations

import http.client
import itertools
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import weakref
from hashlib import blake2b
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.resilience import (FLEET_RESPAWN_POLICY,
                                                   FleetUnavailableError,
                                                   ModelLoadError,
                                                   ReloadRejectedError,
                                                   WorkerProxyError)
from deeplearning4j_tpu.serving.server import _ServingHTTPServer
from deeplearning4j_tpu.util import telemetry as tm

#: default prompt-head length hashed for prefix affinity. 16 tokens cover
#: a shared system-prompt head at one radix-cache block (block_size=16)
#: while still splitting prompts that diverge early; the full candidate
#: set is a declared tuning dimension (tuning/space.py AffinityHeadSpace,
#: env override DL4J_TPU_AFFINITY_HEAD).
DEFAULT_AFFINITY_HEAD = 16


def default_affinity_head() -> int:
    try:
        return int(os.environ.get("DL4J_TPU_AFFINITY_HEAD",
                                  DEFAULT_AFFINITY_HEAD))
    except ValueError:
        return DEFAULT_AFFINITY_HEAD


# ---------------------------------------------------------------- hashing
def rendezvous_score(key: bytes, member: str) -> int:
    """HRW score of ``member`` for ``key``: a keyed blake2b digest — NOT
    Python ``hash()``, which is salted per process and would make every
    router instance (and every respawn) disagree about placement."""
    h = blake2b(key + b"\x00" + member.encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def rendezvous_pick(key: bytes, members: Sequence[str]) -> str:
    """The ring member owning ``key``: highest rendezvous score wins.
    Order-independent in ``members``; removing one member moves ONLY the
    keys it owned (the classic HRW minimal-disruption property — asserted
    in tests/test_fleet.py)."""
    if not members:
        raise ValueError("rendezvous_pick: empty member set")
    return max(members, key=lambda m: (rendezvous_score(key, m), m))


def affinity_key(model_id: str, prompt_tokens, head: int) -> Optional[bytes]:
    """Routing key for a generate request: the model id + the first
    ``head`` prompt tokens (the shared-system-prompt region the radix
    cache deduplicates). None when affinity is off (head<=0) or there is
    no prompt — the request falls back to least-loaded."""
    if head <= 0 or not prompt_tokens:
        return None
    toks = [int(t) for t in list(prompt_tokens)[:head]]
    return json.dumps([model_id, toks]).encode()


# ------------------------------------------------------------ proxy errors
class _ProxyConnError(RuntimeError):
    """One proxy attempt failed at the connection level (failover-able)."""


class _ProxyTimeoutError(RuntimeError):
    """The worker accepted the request but the response timed out. NOT
    failed over — the worker may still be executing it; duplicating the
    work would double load exactly when the fleet is slowest. Maps to
    504."""


class FleetWorker:
    """One worker slot: the process handle (when spawned), its URL, health
    as seen by the poller, the in-flight depth the router tracks, and a
    small pool of persistent connections."""

    def __init__(self, worker_id: str, *, url: Optional[str] = None,
                 adopted: bool = False, max_pool: int = 32):
        self.worker_id = worker_id
        self.adopted = adopted
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        # up | booting | backoff | dead | stopping
        self.state = "booting"
        self.healthy = False
        self.draining = False
        self.inflight = 0
        self.restarts = 0
        self.consecutive_poll_failures = 0
        self.next_spawn_t = 0.0
        self.healthy_since: Optional[float] = None
        self.ready_file: Optional[str] = None
        self.log_path: Optional[str] = None
        self.spawned_at = 0.0
        self.models: Dict[str, dict] = {}  # /v1/models snapshot
        self._max_pool = int(max_pool)
        self._conns: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()
        if url is not None:
            self.set_url(url)
            self.state = "up"

    # ------------------------------------------------------------ address
    def set_url(self, url: str):
        m = re.match(r"^https?://([^:/]+):(\d+)/?$", url)
        if not m:
            raise ValueError(f"worker url must be http://host:port, "
                             f"got {url!r}")
        self.host, self.port = m.group(1), int(m.group(2))

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}"

    @property
    def in_ring(self) -> bool:
        return self.state == "up" and self.healthy and not self.draining

    @property
    def alive(self) -> bool:
        if self.adopted:
            return self.state == "up"
        return self.proc is not None and self.proc.poll() is None

    # ------------------------------------------------------- in-flight
    def inc_inflight(self):
        with self._lock:
            self.inflight += 1

    def dec_inflight(self):
        with self._lock:
            self.inflight = max(0, self.inflight - 1)

    # ------------------------------------------------- connection pool
    def acquire_conn(self, timeout_s: float
                     ) -> Tuple[http.client.HTTPConnection, bool]:
        """(connection, was_reused). Reused connections may be stale
        (worker restarted behind the keep-alive socket); the proxy retries
        once on a fresh one before declaring a connection failure."""
        with self._lock:
            if self._conns:
                conn = self._conns.pop()
                conn.timeout = timeout_s
                if conn.sock is not None:
                    conn.sock.settimeout(timeout_s)
                return conn, True
        if self.port is None:
            raise _ProxyConnError(f"{self.worker_id}: no address yet")
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s), False

    def release_conn(self, conn: http.client.HTTPConnection):
        with self._lock:
            if len(self._conns) < self._max_pool:
                self._conns.append(conn)
                return
        conn.close()

    def close_conns(self):
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    # ------------------------------------------------------------ status
    def describe(self) -> dict:
        models = {}
        for mid, doc in self.models.items():
            entry = {"version": doc.get("version"),
                     "queue_depth": doc.get("queue_depth"),
                     "breaker": (doc.get("breaker") or {}).get("state")
                     if isinstance(doc.get("breaker"), dict)
                     else doc.get("breaker")}
            hit = doc.get("prefix_hit_rate")
            if hit is None:
                cache = (doc.get("kv_pool") or {}).get("prefix_cache") or {}
                hit = cache.get("hit_rate")
            if hit is not None:
                entry["prefix_cache_hit_rate"] = hit
            models[mid] = entry
        return {
            "url": self.url,
            "pid": self.pid,
            "state": self.state,
            "adopted": self.adopted,
            "alive": self.alive,
            "healthy": self.healthy,
            "draining": self.draining,
            "in_ring": self.in_ring,
            "inflight": self.inflight,
            "restarts": self.restarts,
            "models": models,
        }


class FleetRouter:
    """Front-tier router over N worker processes (see module docstring).

    ``spec`` is the worker boot recipe (:func:`fleet_spec`): models as
    ModelSerializer archives + register/ServingModel kwargs — what
    ``serving.fleet_worker`` replays in each worker process. Alternatively
    ``adopt`` takes a list of already-running worker URLs (supervision and
    respawn are then off: the fleet does not own those processes).

    Knobs: ``affinity_head`` (prompt-head tokens hashed for affinity, 0
    disables; default ``DL4J_TPU_AFFINITY_HEAD`` or 16 — a declared
    tuning dimension), ``overflow_depth`` (in-flight depth at which an
    affinity pick spills to least-loaded), ``health_interval_s`` (poller
    cadence), ``respawn``/``max_restarts`` (supervisor budget; the budget
    resets after ``restart_reset_s`` healthy seconds, the scheduler
    watchdog convention), ``boot_timeout_s`` (spawn → ready deadline).
    """

    def __init__(self, spec: Optional[dict] = None, n_workers: int = 2, *,
                 adopt: Optional[Sequence[str]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 name: str = "fleet",
                 affinity_head: Optional[int] = None,
                 overflow_depth: int = 8,
                 health_interval_s: float = 0.25,
                 respawn: bool = True, max_restarts: int = 8,
                 restart_reset_s: float = 30.0,
                 boot_timeout_s: float = 180.0,
                 request_timeout_s: float = 60.0,
                 fleet_dir: Optional[str] = None,
                 worker_env: Optional[dict] = None):
        if spec is None and not adopt:
            raise ValueError("FleetRouter needs a worker spec or adopt=[urls]")
        self.spec = spec
        self.name = name
        self.host = host
        self.port = port
        self.affinity_head = (default_affinity_head()
                              if affinity_head is None else int(affinity_head))
        self.overflow_depth = int(overflow_depth)
        self.health_interval_s = float(health_interval_s)
        self.respawn = bool(respawn) and spec is not None
        self.max_restarts = int(max_restarts)
        self.restart_reset_s = float(restart_reset_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.fleet_dir = fleet_dir
        self.worker_env = dict(worker_env or {})
        self.workers: List[FleetWorker] = []
        if adopt:
            for i, url in enumerate(adopt):
                self.workers.append(
                    FleetWorker(f"w{i}", url=url, adopted=True))
        else:
            for i in range(int(n_workers)):
                self.workers.append(FleetWorker(f"w{i}"))
        self._by_id = {w.worker_id: w for w in self.workers}
        self._spec_path: Optional[str] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._poller: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._stopping = False
        self._reload_lock = threading.Lock()
        self._rr = itertools.count()
        self._decisions = {"affinity": 0, "least_loaded": 0, "failover": 0}
        self._decisions_lock = threading.Lock()

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "FleetRouter":
        if self.spec is not None:
            if self.fleet_dir is None:
                self.fleet_dir = tempfile.mkdtemp(prefix="dl4j_fleet_")
            os.makedirs(self.fleet_dir, exist_ok=True)
            self._spec_path = os.path.join(self.fleet_dir, "spec.json")
            self._write_spec()
            for w in self.workers:
                self._spawn(w)
            deadline = time.monotonic() + self.boot_timeout_s
            for w in self.workers:
                if not self._wait_ready(w, deadline):
                    self.stop()
                    raise RuntimeError(
                        f"fleet worker {w.worker_id} failed to become "
                        f"ready within {self.boot_timeout_s:.0f}s "
                        f"(log: {w.log_path})")
        else:
            # adopted workers: one synchronous poll so the ring is correct
            # before the first request
            for w in self.workers:
                self._poll_worker(w)
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name=f"{self.name}-health")
        self._poller.start()
        self._supervisor = threading.Thread(target=self._supervise_loop,
                                            daemon=True,
                                            name=f"{self.name}-supervise")
        self._supervisor.start()
        handler = _make_fleet_handler(self)
        self._httpd = _ServingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name=f"{self.name}-http")
        self._thread.start()
        _FLEETS.add(self)
        tm.set_health(f"serving.fleet.{self.name}", True,
                      f"{len(self._ring())}/{len(self.workers)} in ring "
                      f"on {self.url}")
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def worker(self, worker_id: str) -> FleetWorker:
        return self._by_id[worker_id]

    def stop(self, kill_timeout_s: float = 10.0):
        """Stop the front tier and the worker processes it owns (SIGTERM →
        graceful worker drain → SIGKILL stragglers). Adopted workers are
        left running — the fleet never owned them."""
        self._stopping = True
        self._stop_evt.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for w in self.workers:
            w.state = "stopping"
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + kill_timeout_s
        for w in self.workers:
            if w.proc is None:
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            w.close_conns()
        for w in self.workers:
            w.close_conns()
        _FLEETS.discard(self)
        tm.set_health(f"serving.fleet.{self.name}", True, "stopped")

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------ spawning
    def _write_spec(self):
        tmp = f"{self._spec_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.spec, f, indent=1)
        os.replace(tmp, self._spec_path)  # atomic: a respawn mid-write
        # never reads a torn spec (the ModelSerializer publish idiom)

    def _spawn(self, w: FleetWorker):
        w.ready_file = os.path.join(self.fleet_dir,
                                    f"{w.worker_id}.ready.json")
        w.log_path = os.path.join(self.fleet_dir, f"{w.worker_id}.log")
        try:
            os.unlink(w.ready_file)
        except OSError:
            pass
        env = dict(os.environ)
        env.update({str(k): str(v)
                    for k, v in (self.spec.get("env") or {}).items()})
        env.update({str(k): str(v) for k, v in self.worker_env.items()})
        cmd = [sys.executable, "-m",
               "deeplearning4j_tpu.serving.fleet_worker",
               "--spec", self._spec_path,
               "--worker-id", w.worker_id,
               "--ready-file", w.ready_file]
        with open(w.log_path, "ab") as logf:
            w.proc = subprocess.Popen(cmd, stdout=logf,
                                      stderr=subprocess.STDOUT, env=env)
        w.pid = w.proc.pid
        w.state = "booting"
        w.healthy = False
        w.healthy_since = None
        w.consecutive_poll_failures = 0
        w.port = None
        w.spawned_at = time.monotonic()
        w.close_conns()
        tm.counter("serving.fleet.worker_spawns_total", fleet=self.name,
                   worker=w.worker_id)

    def _try_adopt_ready(self, w: FleetWorker) -> bool:
        """Read the worker's ready file if it appeared since the spawn."""
        if w.port is not None or not w.ready_file:
            return w.port is not None
        try:
            # the file was unlinked before the spawn, so its existence
            # means THIS incarnation finished warmup and bound its port
            with open(w.ready_file) as f:
                doc = json.load(f)
            w.host = doc.get("host") or "127.0.0.1"
            w.port = int(doc["port"])
            w.pid = int(doc.get("pid", w.pid or 0)) or w.pid
            return True
        except (OSError, ValueError, KeyError):
            return False

    def _wait_ready(self, w: FleetWorker, deadline: float) -> bool:
        while time.monotonic() < deadline:
            if w.proc is not None and w.proc.poll() is not None:
                return False  # died during boot
            if self._try_adopt_ready(w):
                self._poll_worker(w)
                if w.healthy:
                    w.state = "up"
                    w.healthy_since = time.monotonic()
                    return True
            time.sleep(0.1)
        return False

    # ------------------------------------------------------------- polling
    def _worker_get(self, w: FleetWorker, path: str,
                    timeout_s: float = 5.0) -> Tuple[int, bytes]:
        conn = http.client.HTTPConnection(w.host, w.port, timeout=timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _poll_worker(self, w: FleetWorker):
        if w.port is None or w.state in ("backoff", "dead", "stopping"):
            return
        try:
            status, body = self._worker_get(w, "/healthz", timeout_s=3.0)
            doc = json.loads(body)
            w.draining = bool(
                (doc.get("serving") or {}).get("draining", False))
            w.healthy = status == 200
            w.consecutive_poll_failures = 0
            if w.healthy and w.state == "booting":
                w.state = "up"
            if w.healthy and w.healthy_since is None:
                w.healthy_since = time.monotonic()
            if not w.healthy:
                w.healthy_since = None
            mstatus, mbody = self._worker_get(w, "/v1/models", timeout_s=3.0)
            if mstatus == 200:
                mdoc = json.loads(mbody)
                w.models = mdoc.get("models", {})
                if mdoc.get("draining"):
                    w.draining = True
        except (OSError, http.client.HTTPException, ValueError):
            w.consecutive_poll_failures += 1
            # 3 consecutive failed probes drop the worker from the ring
            # (one flaky probe must not churn placement and cold-start
            # every prefix cache downstream of a rendezvous reshuffle)
            if w.consecutive_poll_failures >= 3:
                w.healthy = False
                w.healthy_since = None

    def _poll_loop(self):
        while not self._stop_evt.wait(self.health_interval_s):
            for w in list(self.workers):
                self._poll_worker(w)

    # --------------------------------------------------------- supervision
    def _supervise_loop(self):
        while not self._stop_evt.wait(0.2):
            now = time.monotonic()
            for w in list(self.workers):
                if w.adopted or w.state == "stopping":
                    continue
                rc = w.proc.poll() if w.proc is not None else None
                if rc is not None and w.state in ("up", "booting"):
                    # the process is gone (SIGKILL, OOM, crash): out of
                    # the ring NOW — in-flight proxies to it fail over —
                    # then respawn under backoff
                    w.state = "dead"
                    w.healthy = False
                    w.healthy_since = None
                    w.close_conns()
                    tm.counter("serving.fleet.worker_deaths_total",
                               fleet=self.name, worker=w.worker_id)
                    if self.respawn and w.restarts < self.max_restarts:
                        w.restarts += 1
                        delays = FLEET_RESPAWN_POLICY.delays() or [1.0]
                        d = delays[min(w.restarts - 1, len(delays) - 1)]
                        w.next_spawn_t = now + d
                        w.state = "backoff"
                elif w.state == "backoff" and now >= w.next_spawn_t:
                    self._spawn(w)
                elif w.state == "booting":
                    if self._try_adopt_ready(w):
                        pass  # poller promotes to "up" on green healthz
                    elif now - w.spawned_at > self.boot_timeout_s:
                        try:
                            w.proc.kill()
                        except OSError:
                            pass
                        # fall through next tick: poll() != None → dead
                elif (w.state == "up" and w.restarts and
                      w.healthy_since is not None and
                      now - w.healthy_since > self.restart_reset_s):
                    # healthy long enough: forgive past crashes so a
                    # worker that recovered does not run out of budget
                    # over the fleet's lifetime (watchdog convention)
                    w.restarts = 0

    # ------------------------------------------------------------- routing
    def _ring(self) -> List[FleetWorker]:
        return [w for w in self.workers if w.in_ring]

    def _least_loaded(self, ring: Sequence[FleetWorker]) -> FleetWorker:
        # rotating tiebreak: at equal depth (the common idle case) the
        # pick rotates instead of always hitting w0
        rot = next(self._rr) % len(ring)
        order = list(ring[rot:]) + list(ring[:rot])
        return min(order, key=lambda w: w.inflight)

    def _count(self, reason: str):
        tm.counter("serving.fleet.routing_decisions_total", reason=reason,
                   fleet=self.name)
        with self._decisions_lock:
            self._decisions[reason] = self._decisions.get(reason, 0) + 1

    def pick_worker(self, model_id: str, verb: str,
                    body: Optional[dict]) -> Tuple[FleetWorker, str]:
        """(worker, reason) for one request. Raises
        :class:`FleetUnavailableError` when the ring is empty."""
        ring = self._ring()
        if not ring:
            raise FleetUnavailableError(
                f"fleet {self.name!r}: no live workers in the ring")
        key = None
        if verb == "generate" and body is not None:
            prompts = body.get("prompt_tokens", body.get("prompts"))
            if prompts and isinstance(prompts[0], (int, float)):
                first = prompts
            elif prompts:
                first = prompts[0]
            else:
                first = None
            key = affinity_key(model_id, first, self.affinity_head)
        if key is None:
            return self._least_loaded(ring), "least_loaded"
        wid = rendezvous_pick(key, sorted(w.worker_id for w in ring))
        w = self._by_id[wid]
        least = self._least_loaded(ring)
        if w.inflight >= self.overflow_depth and least.inflight < w.inflight:
            # the affinity target is saturated and a peer is strictly
            # shallower: spill — a hot prefix must not starve a worker
            return least, "least_loaded"
        return w, "affinity"

    # -------------------------------------------------------------- proxy
    def _proxy_once(self, w: FleetWorker, method: str, path: str,
                    body: bytes, rid: Optional[str],
                    timeout_s: Optional[float] = None
                    ) -> Tuple[int, bytes, dict]:
        timeout_s = self.request_timeout_s if timeout_s is None else timeout_s
        hdrs = {"Content-Type": "application/json"}
        if rid:
            hdrs["X-Request-Id"] = rid
        fresh_retry = False
        while True:
            conn, reused = w.acquire_conn(timeout_s)
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                out_headers = dict(resp.getheaders())
                if resp.headers.get("Connection", "").lower() == "close":
                    conn.close()
                else:
                    w.release_conn(conn)
                return resp.status, data, out_headers
            except TimeoutError:
                conn.close()
                raise _ProxyTimeoutError(
                    f"{w.worker_id}: no response within {timeout_s:.0f}s")
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                conn.close()
                if reused and not fresh_retry:
                    # a pooled keep-alive socket can be stale (worker
                    # restarted behind it): one fresh-connection retry
                    # before declaring the worker unreachable
                    fresh_retry = True
                    continue
                raise _ProxyConnError(
                    f"{w.worker_id}: {type(e).__name__}: {e}") from e

    def proxy(self, model_id: str, verb: str, method: str, path: str,
              raw: bytes, body: Optional[dict], rid: Optional[str]
              ) -> Tuple[int, bytes, dict]:
        """Route one request and proxy it, failing over across the ring on
        connection-level errors. Returns (status, body, headers) from the
        worker that answered."""
        w, reason = self.pick_worker(model_id, verb, body)
        tried: set = set()
        while True:
            self._count(reason)
            cur = w
            cur.inc_inflight()
            try:
                return self._proxy_once(cur, method, path, raw, rid)
            except _ProxyConnError as e:
                tried.add(cur.worker_id)
                cur.consecutive_poll_failures += 1
                cur.close_conns()
                candidates = [x for x in self._ring()
                              if x.worker_id not in tried]
                if not candidates:
                    raise WorkerProxyError(
                        f"fleet {self.name!r}: every live worker failed "
                        f"at the connection level for {path} "
                        f"(last: {e})") from e
                w = self._least_loaded(candidates)
                reason = "failover"
            finally:
                cur.dec_inflight()

    # ------------------------------------------------------ rolling reload
    def rolling_reload(self, model_id: str, path: str) -> Dict[str, int]:
        """Fan ``POST /v1/models/<id>/reload`` worker-by-worker, waiting
        for each canary-validated swap (the worker's 200) before the next.
        The rest of the ring serves throughout — zero fleet-level shed.
        Returns {worker_id: new_version}. A worker's 409 (structure
        mismatch / failed canary) aborts the roll: already-swapped workers
        keep the new version, the rest keep the old — both validated, and
        the next roll converges them."""
        with self._reload_lock:
            ring = sorted(self._ring(), key=lambda w: w.worker_id)
            if not ring:
                raise FleetUnavailableError(
                    f"fleet {self.name!r}: no live workers to reload")
            payload = json.dumps({"path": path}).encode()
            versions: Dict[str, int] = {}
            for w in ring:
                # a reload restores + warms the archive before swapping:
                # give it more room than a data-plane request
                status, data, _hdrs = self._proxy_once(
                    w, "POST", f"/v1/models/{model_id}/reload", payload,
                    None, timeout_s=max(120.0, self.request_timeout_s))
                try:
                    doc = json.loads(data)
                except ValueError:
                    doc = {}
                if status == 409:
                    raise ReloadRejectedError(
                        f"worker {w.worker_id} rejected the reload: "
                        f"{doc.get('error')}: {doc.get('detail')}")
                if status == 404:
                    raise ModelLoadError(
                        f"worker {w.worker_id}: {doc.get('error')}")
                if status != 200:
                    raise WorkerProxyError(
                        f"worker {w.worker_id} answered {status} to the "
                        f"reload: {doc}")
                versions[w.worker_id] = int(doc.get("version", 0))
                tm.counter("serving.fleet.reloads_total", fleet=self.name,
                           worker=w.worker_id, model=model_id)
            # respawns must load the NEW weights: rewrite the spawn spec
            if self.spec is not None:
                for m in self.spec.get("models", []):
                    if m.get("id") == model_id:
                        m["path"] = path
                if self._spec_path:
                    self._write_spec()
            return versions

    # -------------------------------------------------------------- status
    def status(self) -> dict:
        ring = self._ring()
        with self._decisions_lock:
            decisions = dict(self._decisions)
        return {
            "name": self.name,
            "url": self.url if self._httpd is not None else None,
            "n_workers": len(self.workers),
            "ring": sorted(w.worker_id for w in ring),
            "affinity_head": self.affinity_head,
            "overflow_depth": self.overflow_depth,
            "respawn": self.respawn,
            "routing_decisions": decisions,
            "workers": {w.worker_id: w.describe() for w in self.workers},
        }

    def metrics_text(self) -> str:
        """Fleet-scope Prometheus text: the router's own registry (routing
        counters, ring gauges via the fleet collector) plus every ring
        worker's ``/metrics`` re-exported with a ``worker`` label. Worker
        comment lines are stripped — repeating ``# TYPE`` per worker would
        make the merged exposition unparsable; the label keeps every
        series unique."""
        parts = [tm.install_default_collectors().prometheus_text()]
        for w in self.workers:
            if w.port is None or not w.alive:
                continue
            try:
                status, body = self._worker_get(w, "/metrics", timeout_s=5.0)
            except (OSError, http.client.HTTPException):
                continue
            if status != 200:
                continue
            parts.append(_relabel_metrics(body.decode("utf-8", "replace"),
                                          w.worker_id))
        return "\n".join(p.rstrip("\n") for p in parts if p) + "\n"

    def debug_requests(self, model_id: str, last: Optional[int] = None
                       ) -> List[dict]:
        """Fleet-wide flight-recorder dump: each ring worker's records for
        ``model_id``, tagged with the worker id (the X-Request-Id satellite
        makes these correlate with the caller's ids end to end)."""
        out: List[dict] = []
        q = f"?last={int(last)}" if last else ""
        for w in self.workers:
            if w.port is None or not w.in_ring:
                continue
            try:
                status, body = self._worker_get(
                    w, f"/v1/models/{model_id}/debug/requests{q}")
            except (OSError, http.client.HTTPException):
                continue
            if status != 200:
                continue
            for rec in json.loads(body).get("requests", []):
                rec["worker"] = w.worker_id
                out.append(rec)
        return out


_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)\s*$")


def _relabel_metrics(text: str, worker_id: str) -> str:
    """Inject ``worker="wN"`` as the first label of every series line;
    drop comments (see :meth:`FleetRouter.metrics_text`)."""
    out = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels, value = m.groups()
        if labels:
            labels = '{worker="%s",%s' % (worker_id, labels[1:])
        else:
            labels = '{worker="%s"}' % worker_id
        out.append(f"{name}{labels} {value}")
    return "\n".join(out)


# --------------------------------------------------------------- telemetry
_FLEETS: "weakref.WeakSet[FleetRouter]" = weakref.WeakSet()


def collect_metrics() -> list:
    """Scrape-time fleet gauges for the telemetry default collectors
    (util/telemetry.py ``_collect_fleet``): ring size and per-worker
    health/membership/in-flight/restarts — fresh at every scrape even
    when no request has routed since the last one."""
    rows = []
    for f in list(_FLEETS):
        lab = {"fleet": f.name}
        rows.append(("serving.fleet.ring_size", dict(lab), len(f._ring())))
        rows.append(("serving.fleet.workers", dict(lab), len(f.workers)))
        for w in f.workers:
            wl = {"fleet": f.name, "worker": w.worker_id}
            rows.append(("serving.fleet.worker_healthy", dict(wl),
                         1 if w.healthy else 0))
            rows.append(("serving.fleet.worker_in_ring", dict(wl),
                         1 if w.in_ring else 0))
            rows.append(("serving.fleet.worker_inflight", dict(wl),
                         w.inflight))
            rows.append(("serving.fleet.worker_restarts", dict(wl),
                         w.restarts))
    return rows


def current_status() -> dict:
    """Fleet section for /healthz (util/ui_server.py): per-fleet ring
    membership and routing counters. Empty when no fleet exists."""
    fleets = list(_FLEETS)
    if not fleets:
        return {}
    if len(fleets) == 1:
        return fleets[0].status()
    return {f.name: f.status() for f in fleets}


# ------------------------------------------------------------- spec helper
def fleet_spec(models: Sequence[dict], env: Optional[dict] = None) -> dict:
    """Worker boot recipe for :class:`FleetRouter`. Each model entry:

    - ``id``: model id; ``path``: ModelSerializer archive
    - ``kind``: "classify" | "generate"; ``quantize``: e.g. "int8"
    - ``register``: ModelRouter.register kwargs (max_wait_ms, max_batch,
      queue_limit, …)
    - ``model_kw``: ServingModel kwargs (bucketing as
      {"batch_buckets": [...], "seq_buckets": [...]}, export_dir,
      prefix_cache, prefill_chunk, pool_blocks, …)

    ``env`` is applied to every worker process before jax imports
    (XLA_FLAGS thread pinning, DL4J_TPU_* knobs, …).
    """
    return {"models": [dict(m) for m in models], "env": dict(env or {})}


def _make_fleet_handler(fleet: FleetRouter):
    class FleetHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _send(self, status: int, body: bytes,
                  ctype: str = "application/json", headers=()):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, status: int, obj, headers=()):
            self._send(status, json.dumps(obj).encode(), headers=headers)

        def _relay(self, status: int, data: bytes, headers: dict):
            """Relay a worker response verbatim: status, body bytes, and
            the headers that carry contract semantics — X-Request-Id (the
            flight-recorder correlation id the worker echoed) and
            Retry-After (the worker's backoff hint on 429/503) MUST cross
            the hop unmodified; minting a fresh id or dropping the hint
            here would break both satellites this layer exists to keep."""
            passthrough = []
            for k in ("X-Request-Id", "Retry-After"):
                v = headers.get(k)
                if v is not None:
                    passthrough.append((k, v))
            ctype = headers.get("Content-Type", "application/json")
            self._send(status, data, ctype=ctype, headers=passthrough)

        def do_GET(self):
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            parts = u.path.strip("/").split("/")
            if u.path == "/metrics":
                self._send(200, fleet.metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif u.path == "/healthz":
                ring = fleet._ring()
                body = {"status": "ok" if ring else "unhealthy",
                        "ring": sorted(w.worker_id for w in ring),
                        "workers": len(fleet.workers)}
                self._send_json(200 if ring else 503, body)
            elif u.path == "/v1/fleet":
                self._send_json(200, fleet.status())
            elif u.path in ("/v1/models", "/v1/models/"):
                # the fleet mirrors a worker's registry (workers are
                # homogeneous by construction: one spec)
                try:
                    status, data, headers = fleet.proxy(
                        "", "models", "GET", "/v1/models", b"", None, None)
                    self._relay(status, data, headers)
                except FleetUnavailableError as e:
                    self._send_json(
                        503, {"error": str(e)},
                        headers=[("Retry-After",
                                  str(int(max(1, e.retry_after_s))))])
                except (WorkerProxyError, _ProxyTimeoutError) as e:
                    self._send_json(502, {"error": str(e)})
            elif len(parts) == 5 and parts[:2] == ["v1", "models"] \
                    and parts[3:] == ["debug", "requests"]:
                try:
                    last = int(parse_qs(u.query).get("last", [0])[0]) or None
                except ValueError:
                    last = None
                self._send_json(200, {
                    "model": parts[2],
                    "requests": fleet.debug_requests(parts[2], last=last)})
            else:
                self._send_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            # read the body on EVERY path (keep-alive framing — same rule
            # as the worker server)
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b""
            parts = self.path.strip("/").split("/")
            if len(parts) != 4 or parts[:2] != ["v1", "models"] \
                    or parts[3] not in ("infer", "generate", "reload"):
                self._send_json(404, {"error": f"no route {self.path}"})
                return
            model_id, verb = parts[2], parts[3]
            from deeplearning4j_tpu.serving.scheduler import new_request_id

            rid = self.headers.get("X-Request-Id") or new_request_id()
            rid_hdr = [("X-Request-Id", rid)]
            try:
                if verb == "reload":
                    body = json.loads(raw or b"{}")
                    versions = fleet.rolling_reload(model_id, body["path"])
                    self._send_json(200, {"model": model_id,
                                          "versions": versions,
                                          "request_id": rid},
                                    headers=rid_hdr)
                    return
                try:
                    body = json.loads(raw or b"{}")
                except ValueError:
                    body = None  # the worker's 400 is the contract owner
                status, data, headers = fleet.proxy(
                    model_id, verb, "POST", self.path, raw, body, rid)
                self._relay(status, data, headers)
            except FleetUnavailableError as e:
                self._send_json(
                    503, {"error": type(e).__name__, "detail": str(e),
                          "request_id": rid},
                    headers=[("Retry-After",
                              str(int(max(1, e.retry_after_s))))] + rid_hdr)
            except (ModelLoadError, ReloadRejectedError) as e:
                self._send_json(409, {"error": type(e).__name__,
                                      "detail": str(e),
                                      "request_id": rid},
                                headers=rid_hdr)
            except WorkerProxyError as e:
                self._send_json(502, {"error": type(e).__name__,
                                      "detail": str(e),
                                      "request_id": rid},
                                headers=rid_hdr)
            except _ProxyTimeoutError as e:
                self._send_json(504, {"error": "worker timeout",
                                      "detail": str(e),
                                      "request_id": rid},
                                headers=rid_hdr)
            except (KeyError, ValueError, TypeError) as e:
                self._send_json(400, {"error": f"bad request: {e!r}"},
                                headers=rid_hdr)
            except Exception as e:  # noqa: BLE001 — the front tier must
                self._send_json(500, {"error": repr(e)},  # never die
                                headers=rid_hdr)

    return FleetHandler
