"""Planet-scale decode path: paged KV cache + speculative decoding + int8.

The decode tier of the model server (docs/SERVING.md#paged-kv--speculative-
decode): a decoder-only LM built from the native transformer layers
(``BertEmbeddingLayer`` → ``TransformerEncoderBlock(causal=True)`` × N →
``RnnOutputLayer``, e.g. ``zoo.bert.Bert(causal=True, task="mlm")``) served
by compile-once executables:

- **prefill** — one causal forward over the whole prompt. Prompt lengths
  round up to ``seq_buckets``; the prompt's K/V scatter into the paged
  block pool through each stream's page table (serving/paged.py).
- **decode_step** — one token per call over the page table: gather the
  stream's K/V rows out of the slot-flat pool, attend ``k_pos <=
  position``, scatter the new token's K/V at its slot. The page table is
  DATA, not shape, so ONE executable (per batch bucket) serves every mix
  of context lengths with zero steady-state recompiles — and the pool is
  shared, so memory scales with actual tokens, not ``streams ×
  max_length`` (the ``concurrent_streams_per_device`` headline).
- **verify** — the speculative-decoding window: a small DRAFT net
  (``Bert(causal=True)`` tiny, loaded per-model via the router) proposes
  ``spec_tokens`` greedy tokens one cheap step at a time; the TARGET
  verifies the whole window in ONE batched step through the paged cache
  and emits every leading token the draft got right plus one
  correction/bonus token from its own logits. Greedy speculative output
  is therefore TOKEN-IDENTICAL to greedy non-speculative output by
  construction — every emitted token is the target's own argmax —
  proven in tests/test_paged_decode.py including a draft that is always
  wrong (k rejections per round, still identical, just slower).
  Rejected tails roll back page-table state exactly: positions are host
  bookkeeping, and the stale K/V rows of rejected slots are provably
  overwritten before any read (serving/paged.py module doc).
  ``temperature > 0`` falls back to the plain per-token sampling loop —
  verify-consistent by construction (same program, same key stream as
  the non-speculative path).

Admission: a batch whose streams cannot all get blocks sheds with
:class:`~deeplearning4j_tpu.serving.resilience.PoolExhaustedError`
(HTTP 429 + Retry-After, flight-recorder cause ``pool_exhausted``)
BEFORE any device work; blocks free on completion/eos (the decode loop
exits early once every live row emitted eos) and on shed.

Weight-only int8 (serving/quantize.py): ``quantize="int8"`` stores
resident int8 weights + per-channel scales and dequantizes inside these
same executables; the fp32 path is bit-unchanged.

Exactness contracts (tests/test_paged_decode.py + tests/test_serving.py):
greedy decode through the paged cache == greedy decode through the
contiguous r13 cache == greedy O(T²) full recompute, token-for-token.
``generate_full_recompute`` remains the oracle. All programs are plain
``jax.jit`` with trace markers, so the CompileWatcher (and
``serving.recompiles_total``) sees every signature they ever trace.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.serving.paged import (BlockPool, PoolExhaustedError,
                                              PrefixCache,
                                              default_pool_blocks)
from deeplearning4j_tpu.serving.quantize import maybe_quantize
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import note_trace


def _decoder_parts(net, what: str):
    """Validate and split a decoder-only MLN into (emb, blocks, head)."""
    from deeplearning4j_tpu.nn.transformer import (BertEmbeddingLayer,
                                                   TransformerEncoderBlock)

    layers = net.layers
    if not layers or not isinstance(layers[0], BertEmbeddingLayer):
        raise ValueError(f"{what} needs a BertEmbeddingLayer input "
                         "(e.g. zoo.bert.Bert(causal=True, task='mlm'))")
    blocks = layers[1:-1]
    if not blocks or not all(isinstance(b, TransformerEncoderBlock)
                             for b in blocks):
        raise ValueError(f"{what} needs TransformerEncoderBlock middle "
                         "layers")
    if not all(b.causal for b in blocks):
        raise ValueError(f"{what} needs causal=True blocks — a "
                         "bidirectional encoder cannot decode "
                         "autoregressively")
    if not hasattr(layers[-1], "_logits"):
        raise ValueError(f"{what} needs a per-token logits head "
                         "(RnnOutputLayer, task='mlm')")
    return layers[0], list(blocks), layers[-1]


class Generator:
    """Compile-once decode serving head over a decoder-only
    MultiLayerNetwork (module doc).

    ``batch_buckets`` / ``prefill_buckets`` default to the model conf's
    bucketing knobs (ONE policy source of truth with training and the
    classify tier); ``max_length`` defaults to the embedding layer's
    ``max_position`` and bounds prompt + generated tokens.

    Decode engine knobs: ``paged`` (default True — the r13 contiguous
    cache remains as ``paged=False``, the identity oracle), ``block_size``
    / ``pool_blocks`` (pool geometry; default pool holds the largest
    batch bucket at full context, so admission only bites when sized
    down deliberately), ``draft_net`` + ``spec_tokens`` (speculative
    decoding — the draft runs its own small contiguous cache), and
    ``quantize`` ("int8" weight-only serving)."""

    def __init__(self, net, *, max_length: Optional[int] = None,
                 batch_buckets=None, prefill_buckets=None,
                 paged: bool = True, block_size: int = 16,
                 pool_blocks: Optional[int] = None,
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 draft_net=None, spec_tokens: int = 4,
                 quantize: Optional[str] = None,
                 model_id: str = ""):
        self.emb, self.blocks, self.head = _decoder_parts(net, "Generator")
        self.net = net
        self.model_id = str(model_id)
        self.max_length = int(max_length or self.emb.max_position)
        conf_policy = BucketingPolicy.from_conf(getattr(net, "conf", None))
        if batch_buckets is None and conf_policy is not None:
            batch_buckets = conf_policy.batch_buckets
        if prefill_buckets is None and conf_policy is not None:
            prefill_buckets = conf_policy.seq_buckets
        self.policy = BucketingPolicy(
            batch_buckets=batch_buckets or "pow2",
            seq_buckets=prefill_buckets or "pow2")
        self.paged = bool(paged)
        self.block_size = int(block_size)
        self._qp = maybe_quantize(net, quantize, model_id=self.model_id)
        # contiguous programs: the paged=False engine, the full-recompute
        # oracle's prefill, and the draft substrate
        self._prefill_jit = jax.jit(self._prefill)
        self._decode_jit = jax.jit(self._decode)
        self.pool: Optional[BlockPool] = None
        if self.paged:
            # an AUTO-sized pool (pool_blocks=None) grows on demand
            # (_admit) instead of shedding — the r13 contiguous engine
            # never refused a batch for cache memory, and a dynamic
            # ("pow2") bucket policy has no largest batch to size for.
            # Admission control = the shed contract only applies when the
            # operator PINNED a budget.
            self._pool_auto = pool_blocks is None
            if pool_blocks is None:
                bb = self.policy.batch_buckets
                pool_blocks = default_pool_blocks(
                    bb if isinstance(bb, tuple) else (32,),
                    self.max_length, self.block_size)
            self.pool = BlockPool(self.blocks, block_size=self.block_size,
                                  num_blocks=int(pool_blocks),
                                  max_length=self.max_length,
                                  model_id=self.model_id)
            # pools are DONATED through the paged programs (the hot loop
            # must not copy the whole pool per token) — every call site
            # threads the returned pools back into self.pool.pools
            self._prefill_paged_jit = jax.jit(self._prefill_paged,
                                              donate_argnums=(1,))
            self._decode_paged_jit = jax.jit(self._decode_paged,
                                             donate_argnums=(1,))
            self._verify_paged_jit = jax.jit(self._verify_paged,
                                             donate_argnums=(1,))
            self._prefill_window_jit = jax.jit(self._prefill_window_paged,
                                               donate_argnums=(1,))
            self._copy_block_jit = jax.jit(self._copy_block,
                                           donate_argnums=(0,))
        # prefix cache (ISSUE 16 tentpole): a radix trie over prompt
        # prefixes → block chains, so N streams with a common head hold
        # ONE physical copy and resume prefill past it. Off by default —
        # the bit-path of prefix_cache=False is the r20 engine unchanged.
        self.prefix_cache = bool(prefix_cache) and self.paged
        self.cache: Optional[PrefixCache] = (
            PrefixCache(self.pool) if self.prefix_cache else None)
        # chunked prefill: cap the window width so a long-prompt burst
        # yields the device to queued decode batches between chunks
        if prefill_chunk is not None and not self.paged:
            raise ValueError("prefill_chunk needs paged=True (the chunk "
                             "window is a paged program)")
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        #: nesting depth of generate() — > 1 while a chunk-yield runs a
        #: nested decode batch; nested runs never grow/reset the pool
        self._depth = 0
        #: bumped whenever the device pool buffers are replaced (growth /
        #: exception reset) — a chunk loop re-checks it after yielding
        self._pool_epoch = 0
        # speculative decoding: the draft is a plain contiguous-cache
        # generator over the (tiny) draft net — same bucket policy, so
        # draft prefill shapes always match the target's prep
        self.spec_tokens = int(spec_tokens)
        self.draft: Optional[Generator] = None
        if draft_net is not None:
            if not self.paged:
                raise ValueError("speculative decoding needs paged=True "
                                 "(the verify window is a paged program)")
            self.draft = Generator(
                draft_net, max_length=self.max_length,
                batch_buckets=self.policy.batch_buckets,
                prefill_buckets=self.policy.seq_buckets,
                paged=False, model_id=f"{self.model_id}/draft"
                if self.model_id else "")
            if self.draft.emb.max_position < self.max_length:
                raise ValueError(
                    f"draft net max_position {self.draft.emb.max_position} "
                    f"< target max_length {self.max_length}")

    # ----------------------------------------------------------- parameters
    def _raw_params(self):
        """What the traced programs take: the live fp32 tree (bit-unchanged
        legacy path) or the resident (int8 leaves, scales) pair."""
        if self._qp is None:
            return self.net.params
        return self._qp.args()

    def _params_of(self, raw):
        """Inside-jit: raw → the parameter tree the layers consume. For
        int8 this IS the in-forward dequantize (serving/quantize.py)."""
        if self._qp is None:
            return raw
        return self._qp.rebuild(raw)

    # ------------------------------------------------------ traced programs
    def _prefill(self, raw, tokens, lengths):
        """Contiguous-cache prefill: tokens (B, T) int32, lengths (B,)
        int32 → (next-token logits (B, V), caches). Padding rows/positions
        are masked out of every attention read; the cache rows they write
        are overwritten by generation before they are ever visible
        (nn/transformer.py)."""
        note_trace("serving.prefill", tokens, lengths)  # trace-time only
        params = self._params_of(raw)
        b, t = tokens.shape
        x, _ = self.emb.apply(params[0], {}, tokens)
        pad_mask = (jnp.arange(t)[None, :]
                    < lengths[:, None]).astype(x.dtype)
        caches = []
        for i, blk in enumerate(self.blocks):
            cache = blk.init_cache(b, self.max_length, x.dtype)
            x, cache = blk.prefill(params[i + 1], x, cache, mask=pad_mask)
            caches.append(cache)
        h_last = x[jnp.arange(b), lengths - 1]
        logits = self.head._logits(params[-1], h_last)
        return logits, caches

    def _decode(self, raw, caches, tokens, positions):
        """One contiguous-cache autoregressive step: tokens (B,) placed at
        per-row ``positions`` (B,) → (next-token logits (B, V), caches)."""
        note_trace("serving.decode_step", tokens, positions)
        params = self._params_of(raw)
        x = self.emb.embed_step(params[0], tokens, positions)[:, None, :]
        new_caches = []
        for i, blk in enumerate(self.blocks):
            x, cache = blk.decode_step(params[i + 1], x, caches[i], positions)
            new_caches.append(cache)
        logits = self.head._logits(params[-1], x[:, 0])
        return logits, new_caches

    def _slots_of(self, tables):
        """Page tables (B, max_blocks) → per-position flat slot indices
        (B, max_length). Sliced to EXACTLY max_length so the gathered
        layout — and therefore every attention reduction — has the same
        shape as the contiguous cache (the bit-level identity argument,
        ops/attention.paged_kv_gather)."""
        bs = self.block_size
        s = tables[:, :, None] * bs + jnp.arange(bs)[None, None, :]
        return s.reshape(tables.shape[0], -1)[:, :self.max_length]

    def _prefill_paged(self, raw, pools, tokens, lengths, tables):
        """Paged prefill: same causal forward as ``_prefill`` (the prompt
        attention runs over in-register K/V, so the logits are identical),
        with every position's K/V scattered through the page table."""
        note_trace("serving.prefill_paged", tokens, lengths)
        params = self._params_of(raw)
        b, t = tokens.shape
        x, _ = self.emb.apply(params[0], {}, tokens)
        pad_mask = (jnp.arange(t)[None, :]
                    < lengths[:, None]).astype(x.dtype)
        slots = self._slots_of(tables)[:, :t]
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.prefill_paged(params[i + 1], x, pools[i], slots,
                                        mask=pad_mask)
            new_pools.append(pool)
        h_last = x[jnp.arange(b), lengths - 1]
        logits = self.head._logits(params[-1], h_last)
        return logits, new_pools

    def _decode_paged(self, raw, pools, tables, tokens, positions, limits):
        """One paged autoregressive step (module doc). ``limits`` (B,) is
        each stream's last valid position — a row that finished while its
        batch keeps decoding redirects overrun writes to the trash block
        instead of clobbering a live slot."""
        note_trace("serving.decode_step_paged", tokens, positions)
        params = self._params_of(raw)
        x = self.emb.embed_step(params[0], tokens, positions)[:, None, :]
        slots = self._slots_of(tables)
        pos_w = positions[:, None]
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.decode_window_paged(params[i + 1], x, pools[i],
                                              slots, pos_w, limits=limits)
            new_pools.append(pool)
        logits = self.head._logits(params[-1], x[:, 0])
        return logits, new_pools

    def _verify_paged(self, raw, pools, tables, window, positions0, limits):
        """Speculative verify: ``window`` (B, W) tokens at positions
        ``positions0 + [0..W)`` → per-position next-token logits
        (B, W, V) in ONE batched step. Window K/V are written first, each
        query attends ``k_pos <= its position`` — exactly the sequential
        decode-step semantics, batched over the window."""
        note_trace("serving.verify_paged", window, positions0)
        params = self._params_of(raw)
        w = window.shape[1]
        pos_w = positions0[:, None] + jnp.arange(w)[None, :]
        x = self.emb.embed_window(params[0], window, pos_w)
        slots = self._slots_of(tables)
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.decode_window_paged(params[i + 1], x, pools[i],
                                              slots, pos_w, limits=limits)
            new_pools.append(pool)
        logits = self.head._logits(params[-1], x)
        return logits, new_pools

    def _prefill_window_paged(self, raw, pools, window, positions, tables,
                              limits, last_idx):
        """Resume-from-position prefill over one chunk window: ``window``
        (B, W) prompt tokens at per-row absolute ``positions`` (B, W) —
        each row starts at its own cache-resume point — write-then-attend
        through the page table (``nn/transformer.py``
        ``prefill_resume_paged``), exactly the verify-window semantics,
        so chunked/resumed prefill is bit-identical to whole prefill.
        ``limits`` (B,) = last prompt position (overrun/padding columns
        scatter to trash); ``last_idx`` (B,) selects each row's final-
        prompt-position column for the next-token logits (garbage for
        rows whose prompt ends in another chunk — the host keeps only
        the chunk where each row finishes). Everything but the (batch
        bucket, W-bucket) shape is data: ONE executable per bucket pair,
        zero steady-state recompiles across any hit/miss mix."""
        note_trace("serving.prefill_window_paged", window, positions)
        params = self._params_of(raw)
        # clamp: lockstep chunking runs padding columns past the prompt
        # (and past max_length for short rows) — limit-masked to trash on
        # write, never read back, but the gathers need in-range indices
        pos_w = jnp.minimum(positions, self.max_length - 1)
        x = self.emb.embed_window(params[0], window, pos_w)
        slots = self._slots_of(tables)
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.prefill_resume_paged(params[i + 1], x, pools[i],
                                               slots, pos_w, limits=limits)
            new_pools.append(pool)
        b = window.shape[0]
        h_last = x[jnp.arange(b), last_idx]
        logits = self.head._logits(params[-1], h_last)
        return logits, new_pools

    def _copy_block(self, pools, src, dst):
        """Copy-on-write device copy: duplicate physical block ``src``'s
        rows into ``dst`` across every layer's K and V pool (the COW
        split of serving/paged.py — the table already points at ``dst``;
        this fills it before the suffix prefill overwrites the one
        recomputed row). Block ids are data: one executable ever."""
        note_trace("serving.cow_copy", src, dst)
        bs = self.block_size
        rows_src = src * bs + jnp.arange(bs)
        rows_dst = dst * bs + jnp.arange(bs)
        return [{"k": p["k"].at[rows_dst].set(p["k"][rows_src]),
                 "v": p["v"].at[rows_dst].set(p["v"][rows_src])}
                for p in pools]

    # ------------------------------------------------------------- sampling
    @staticmethod
    def _sample(logits, temperature: float, key):
        if temperature and temperature > 0.0:
            return jax.random.categorical(
                key, logits / jnp.asarray(temperature, logits.dtype), axis=-1
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _prefill_len(self, longest: int) -> int:
        """Prefill shape for the longest prompt: its seq bucket, with
        ``max_length`` as the implicit FINAL bucket — a prompt above the
        largest explicit bucket pads up to max_length instead of tracing a
        fresh per-length executable (the pad-up-not-retrace contract,
        docs/SERVING.md; warmup() primes the max_length shape too)."""
        t = self.policy.bucket_seq(longest)
        top = self.policy.seq_buckets
        if isinstance(top, tuple) and longest > top[-1]:
            return self.max_length
        return min(t, self.max_length)

    def _prep(self, prompts: Sequence[Sequence[int]], max_new_tokens: int):
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({max(lens)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_length ({self.max_length})")
        b_real = len(prompts)
        b = self.policy.bucket_batch(b_real)
        t = self._prefill_len(max(lens))
        tokens = np.zeros((b, t), np.int32)
        lengths = np.ones((b,), np.int32)  # padded rows: 1 fake token
        for i, p in enumerate(prompts):
            tokens[i, :lens[i]] = np.asarray(p, np.int32)
            lengths[i] = lens[i]
        return (jnp.asarray(tokens), jnp.asarray(lengths), b_real, lens)

    @staticmethod
    def _trim_row(row: List[int], max_new: int,
                  eos_id: Optional[int]) -> List[int]:
        row = row[:max_new]
        if eos_id is not None and eos_id in row:
            row = row[: row.index(eos_id) + 1]
        return row

    def _trim(self, stacked, b_real: int, lens, max_new_tokens: int,
              eos_id: Optional[int]) -> List[List[int]]:
        return [self._trim_row([int(v) for v in stacked[i]],
                               max_new_tokens, eos_id)
                for i in range(b_real)]

    # ------------------------------------------------------------ admission
    def _grow(self, need: int):
        """Swap in a pool twice the size (or ``need`` blocks if larger).
        Growth changes the pool shapes, so the NEXT paged calls trace once
        at the new size — a capacity event, not steady state (serving
        configs with finite buckets size the pool to their largest batch
        up front and never reach this branch; the 0-recompile contract is
        asserted there). Old buffers are dropped BEFORE the new
        allocation so device residency never doubles — which also kills
        every cached prefix byte, so the trie flushes first."""
        grown = max(need, 2 * self.pool.num_blocks)
        tm.counter("serving.kv_pool_grown_total", model=self.model_id)
        tm.instant("serving.kv_pool_grown", model=self.model_id,
                   blocks=grown)
        if self.cache is not None:
            self.cache.flush()
        old_peak = self.pool.peak_streams
        self.pool.pools = None  # free before the bigger alloc
        self.pool = BlockPool(self.blocks,
                              block_size=self.block_size,
                              num_blocks=grown,
                              max_length=self.max_length,
                              model_id=self.model_id)
        self.pool.peak_streams = old_peak
        self._pool_epoch += 1
        if self.cache is not None:
            self.cache.rebind(self.pool)

    def _admit(self, lens, max_new: int, batch: int, prompts=None):
        """Reserve every stream's blocks for the WHOLE generation —
        all-or-nothing (PoolExhaustedError → the scheduler's 429 shed) —
        and build the (B, max_blocks) page-table array. An AUTO-sized pool
        (no operator budget) GROWS to fit instead of shedding: reserve
        failed with nothing allocated and pool content never outlives a
        batch — except prefix-cache content, which the grow path flushes
        — so swapping in a larger pool is safe mid-flight. A NESTED batch
        (running inside another batch's chunk-yield, ``_depth > 1``)
        never grows: the outer prefill is mid-write into the current
        buffers.

        Returns ``(tables_list, tables, starts, cow, pending)``:
        per-stream block lists, the device table array, each stream's
        resume position (0 without a cache hit), COW ``(src, dst)`` block
        copies to run before prefill, and the batch's pending trie nodes
        to commit after it."""
        if self.cache is not None and prompts is not None:
            return self._admit_prefix(prompts, lens, max_new, batch)
        counts = [self.pool.blocks_needed(l, max_new) for l in lens]
        try:
            tables_list = self.pool.reserve(counts)
        except PoolExhaustedError:
            if not self._pool_auto or self._depth > 1:
                raise
            self._grow(int(sum(counts)))
            tables_list = self.pool.reserve(counts)
        tables = jnp.asarray(self.pool.table_array(tables_list, batch))
        return tables_list, tables, [0] * len(lens), [], []

    def _admit_prefix(self, prompts, lens, max_new: int, batch: int):
        """Prefix-aware admission: transactional match + reserve + COW +
        trie insert (``_admit_prefix_once``), with a retry ladder on
        exhaustion — evict cache-only blocks first, then (auto pools,
        non-nested only) grow."""
        worst = sum(self.pool.blocks_needed(l, max_new) for l in lens)
        try:
            return self._admit_prefix_once(prompts, lens, max_new, batch)
        except PoolExhaustedError:
            pass
        # second chance: LRU-evict blocks only the trie still holds
        self.cache.evict(worst)
        try:
            return self._admit_prefix_once(prompts, lens, max_new, batch)
        except PoolExhaustedError:
            if not self._pool_auto or self._depth > 1:
                raise
        self._grow(worst)
        return self._admit_prefix_once(prompts, lens, max_new, batch)

    def _admit_prefix_once(self, prompts, lens, max_new: int, batch: int):
        """One admission attempt, all-or-nothing ACROSS THE BATCH: on
        PoolExhaustedError every hold this attempt took — matched-prefix
        increfs, fresh reservations, COW splits, pending trie inserts —
        is rolled back before the raise, so the caller's retry ladder
        (and the 429 shed) always starts from clean allocator state."""
        pool, cache, bs = self.pool, self.cache, self.block_size
        tables_list, starts, cow, pending = [], [], [], []
        hit_tokens = 0
        with pool._lock:
            try:
                for p, l in zip(prompts, lens):
                    blocks, committed = cache.match(p)  # increfs matched
                    need = pool.blocks_needed(l, max_new)
                    try:
                        # matched < need always (max_new >= 1): every
                        # stream owns at least its generation blocks
                        fresh = pool.reserve([need - len(blocks)])[0]
                    except PoolExhaustedError:
                        pool.decref(blocks)  # match-only holds so far
                        raise
                    table = list(blocks) + fresh
                    # resume point: skip committed tokens, but always
                    # recompute >= 1 prompt token for next-token logits
                    start = min(committed, l - 1)
                    if start < committed:
                        # block-aligned full hit: the one recomputed
                        # position l-1 lands INSIDE a shared cached
                        # block — copy-on-write before the prefill
                        bi = start // bs
                        try:
                            nb = pool.cow_split(table[bi])
                        except PoolExhaustedError:
                            pool.release([table])
                            raise
                        cow.append((table[bi], nb))
                        table[bi] = nb
                    pending.extend(cache.insert(p, table))
                    tables_list.append(table)
                    starts.append(start)
                    hit_tokens += start
            except PoolExhaustedError:
                cache.rollback(pending)
                pool.release(tables_list)
                raise
        tm.gauge("serving.prefix_cache_hit_rate",
                 round(cache.hit_rate(), 4), model=self.model_id)
        self._last_hit_tokens = hit_tokens
        tables = jnp.asarray(pool.table_array(tables_list, batch))
        return tables_list, tables, starts, cow, pending

    # ------------------------------------------------------------- decoding
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 16, *, temperature: float = 0.0,
                 key=None, eos_id: Optional[int] = None,
                 trace: bool = False, stats: Optional[Dict] = None,
                 yield_hook=None) -> List[List[int]]:
        """Decode ``prompts``: one prefill + per-token decode steps (or
        speculative verify windows when a draft net is attached and the
        decode is greedy), all on warmed executables. ``temperature=0`` is
        greedy (deterministic); otherwise categorical sampling from
        ``key`` (default PRNGKey(0)) through the plain per-token loop.
        ``trace=True`` (a head-sampled serving batch) emits prefill /
        ``decode_token`` / ``verify`` spans — the per-token ruler of
        docs/OBSERVABILITY.md#request-tracing--slos. ``stats`` (a dict,
        filled in place) receives ``draft_accept_rate`` per row and the
        batch ``spec_accept_rate`` when speculating, plus
        ``prefix_hit_rate`` / ``resumed_positions`` / ``prefill_chunks``
        under the prefix cache / chunked prefill. ``yield_hook``
        (scheduler-provided) is called between prefill chunks so queued
        interactive decode batches can run mid-prefill."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        if not self.paged:
            return self._generate_contiguous(
                prompts, max_new_tokens, temperature=temperature, key=key,
                eos_id=eos_id, trace=trace)
        tokens, lengths, b_real, lens = self._prep(prompts, max_new_tokens)
        batch = int(tokens.shape[0])
        self._depth += 1
        try:
            # admission stays OUTSIDE the reset-on-failure block: a shed
            # allocated nothing and must not trash live pool content
            tables_list, tables, starts, cow, pending = self._admit(
                lens, max_new_tokens, batch, prompts=prompts)
        except BaseException:
            self._depth -= 1
            raise
        if stats is not None and self.cache is not None:
            stats["prefix_hit_rate"] = round(
                sum(starts) / max(1, sum(lens)), 4)
            stats["resumed_positions"] = list(starts)
        try:
            speculate = (self.draft is not None and self.spec_tokens > 0
                         and not (temperature and temperature > 0.0))
            if speculate:
                return self._generate_speculative(
                    tokens, lengths, tables, b_real, lens, max_new_tokens,
                    eos_id=eos_id, trace=trace, stats=stats,
                    starts=starts, cow=cow, pending=pending,
                    yield_hook=yield_hook)
            return self._generate_paged(
                tokens, lengths, tables, b_real, lens, max_new_tokens,
                temperature=temperature, key=key, eos_id=eos_id,
                trace=trace, stats=stats, starts=starts, cow=cow,
                pending=pending, yield_hook=yield_hook)
        except BaseException:
            # a failure mid-decode may have consumed the donated pool
            # buffers — rebuild them (pool CONTENT never outlives a batch
            # except cached prefixes, which _reset_pools flushes; only
            # the host allocator state matters, and release() below
            # restores that)
            if self.cache is not None:
                self.cache.rollback(pending)
            self._reset_pools()
            raise
        finally:
            self._depth -= 1
            # blocks free on completion, eos early-exit, and shed alike
            self.pool.release(tables_list)

    def _reset_pools(self):
        if self.cache is not None:
            # the buffers the cached blocks lived in are being replaced
            self.cache.flush()
        self.pool.pools = [blk.init_pool(self.pool.num_slots)
                           for blk in self.blocks]
        self._pool_epoch += 1

    def _window_width(self, max_rem: int) -> int:
        """Chunk-window width for ``max_rem`` remaining prompt tokens:
        the operator's ``prefill_chunk`` when set, else one bucketed
        window covering the whole remainder (suffix-only resume, no
        interleaving) — either way a shape warmup() primed."""
        if self.prefill_chunk is not None:
            return min(self.prefill_chunk, self.max_length)
        return self._prefill_len(max_rem)

    def _run_prefill(self, raw, tokens, lengths, tables, b_real, lens,
                     starts, cow, pending, tele, stats, yield_hook,
                     speculative: bool = False):
        """Dispatch the prompt phase: COW block copies, then either the
        r20 whole-prompt prefill (bit-path unchanged — no cache hit, no
        chunking) or the resume/chunk window loop, then commit this
        batch's trie nodes. Returns next-token logits (B, V)."""
        batch = int(tokens.shape[0])
        t = int(tokens.shape[1])
        for src, dst in cow:
            pools = self._copy_block_jit(self.pool.pools,
                                         jnp.asarray(src, jnp.int32),
                                         jnp.asarray(dst, jnp.int32))
            self.pool.pools = pools
        t_pf = time.time_ns() if tele else 0
        whole = (not any(starts)) and (self.prefill_chunk is None
                                       or t <= self.prefill_chunk)
        if whole:
            logits, pools = self._prefill_paged_jit(
                raw, self.pool.pools, tokens, lengths, tables)
            self.pool.pools = pools
            n_chunks = 1
        else:
            logits, n_chunks = self._prefill_windowed(
                raw, tokens, lengths, tables, b_real, lens, starts,
                yield_hook)
        if tele:
            tele.event_deferred(
                "serving.generate.prefill", t_pf, time.time_ns(),
                batch=batch, seq=t, paged=True, speculative=speculative,
                prefix_hit=bool(any(starts)),
                resumed=int(sum(starts)), chunks=n_chunks)
        if stats is not None:
            stats["prefill_chunks"] = n_chunks
        if self.cache is not None and pending:
            # the prefill that writes these blocks has been issued —
            # program order guarantees any later read sees the writes
            self.cache.commit(pending)
        return logits

    def _prefill_windowed(self, raw, tokens, lengths, tables, b_real,
                          lens, starts, yield_hook):
        """The resume/chunk window loop (ISSUE 16): every row computes
        only its uncached suffix, ``W`` positions per chunk, through
        ``_prefill_window_paged``. Lockstep chunking — chunk c covers
        per-row absolute positions ``start_i + c*W + [0, W)`` — keeps
        shapes fixed; rows pad with trash-masked columns once their
        prompt is done. Between chunks ``yield_hook`` hands the device
        to queued interactive batches (chunked prefill: a long-prompt
        burst cannot spike decode p99); the pool-epoch check aborts if
        a nested run reset the buffers under us."""
        batch = int(tokens.shape[0])
        t = int(tokens.shape[1])
        tokens_np = np.asarray(tokens)
        lengths_np = np.asarray(lengths)
        starts_np = np.zeros((batch,), np.int32)
        starts_np[:b_real] = np.asarray(starts, np.int32)
        max_rem = max(int(l - s) for l, s in zip(lens, starts))
        w = self._window_width(max_rem)
        n_chunks = math.ceil(max_rem / w)
        limits = jnp.asarray((lengths_np - 1).astype(np.int32))
        final = np.zeros((batch,), object)
        for c in range(n_chunks):
            if c and yield_hook is not None:
                epoch0 = self._pool_epoch
                yield_hook()
                if self._pool_epoch != epoch0:
                    raise RuntimeError(
                        "KV pool reset during chunked-prefill yield — "
                        "aborting the outer batch")
            base = starts_np + c * w
            cols = base[:, None] + np.arange(w, dtype=np.int32)[None, :]
            window = np.take_along_axis(
                tokens_np, np.minimum(cols, t - 1), axis=1)
            window = np.where(cols < lengths_np[:, None], window, 0)
            li = lengths_np - 1 - base
            in_chunk = (li >= 0) & (li < w)
            last_idx = np.clip(li, 0, w - 1).astype(np.int32)
            logits_c, pools = self._prefill_window_jit(
                raw, self.pool.pools, jnp.asarray(window),
                jnp.asarray(cols), tables, limits,
                jnp.asarray(last_idx))
            self.pool.pools = pools
            if in_chunk.any():
                # keep the device rows; host-gather only at the end
                rows = logits_c
                for i in np.nonzero(in_chunk)[0]:
                    final[i] = rows[i]
        tm.counter("serving.chunked_prefill_chunks_total", n_chunks,
                   model=self.model_id)
        logits = jnp.stack([final[i] for i in range(batch)])
        return logits, n_chunks

    def _generate_paged(self, tokens, lengths, tables, b_real, lens,
                        max_new: int, *, temperature: float, key,
                        eos_id: Optional[int], trace: bool, stats=None,
                        starts=(), cow=(), pending=(), yield_hook=None):
        """The plain per-token paged loop (greedy or sampled) — the same
        sampling stream as the contiguous path, so paged==contiguous is
        token-exact (greedy) / stream-exact (sampled)."""
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])
        limits = jnp.asarray(np.asarray(
            [l + max_new - 1 for l in lens]
            + [0] * (batch - b_real), np.int32))

        logits = self._run_prefill(raw, tokens, lengths, tables, b_real,
                                   lens, starts, cow, pending, tele,
                                   stats, yield_hook)
        positions = lengths
        steps = []
        done = np.zeros(b_real, bool)
        key, sub = jax.random.split(key)
        cur = self._sample(logits, temperature, sub)
        for i in range(max_new):
            steps.append(cur)
            if eos_id is not None:
                done |= (np.asarray(cur)[:b_real] == eos_id)
                if done.all():
                    break  # every live stream finished: free blocks early
            if i == max_new - 1:
                break
            t_dt = time.time_ns() if tele else 0
            logits, pools = self._decode_paged_jit(
                raw, self.pool.pools, tables, cur, positions, limits)
            self.pool.pools = pools
            if tele:
                tele.event_deferred("serving.generate.decode_token", t_dt,
                                    time.time_ns(), step=i + 1, batch=batch)
            positions = positions + 1
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        return self._trim(stacked, b_real, lens, max_new, eos_id)

    def _generate_speculative(self, tokens, lengths, tables, b_real, lens,
                              max_new: int, *, eos_id: Optional[int],
                              trace: bool, stats: Optional[Dict],
                              starts=(), cow=(), pending=(),
                              yield_hook=None):
        """Greedy speculative decode (module doc). Every emitted token is
        the TARGET's argmax — the draft only decides how many the verify
        window can commit at once. Prefix sharing applies to the TARGET's
        paged prefill only; the draft keeps its own full contiguous
        prefill (its cache is private, tiny, and never shared)."""
        raw = self._raw_params()
        draft = self.draft
        draft_raw = draft._raw_params()
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])
        w = self.spec_tokens + 1  # window = last accepted + k proposals
        limits_np = np.asarray([l + max_new - 1 for l in lens]
                               + [0] * (batch - b_real), np.int32)
        limits = jnp.asarray(limits_np)

        logits = self._run_prefill(raw, tokens, lengths, tables, b_real,
                                   lens, starts, cow, pending, tele,
                                   stats, yield_hook, speculative=True)
        _, dcaches = draft._prefill_jit(draft_raw, tokens, lengths)

        cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # token AT pos
        pos_np = np.asarray(lengths)  # cur's position, per row
        prev = tokens[jnp.arange(batch), jnp.asarray(pos_np) - 1]
        emitted: List[List[int]] = [[] for _ in range(batch)]
        done = np.zeros(b_real, bool)
        accept_num = np.zeros(batch, np.int64)
        accept_den = np.zeros(batch, np.int64)
        host_cur = np.asarray(cur)
        for i in range(b_real):
            emitted[i].append(int(host_cur[i]))
            if eos_id is not None and int(host_cur[i]) == eos_id:
                done[i] = True

        rounds = 0
        while not done.all() and any(len(emitted[i]) < max_new
                                     for i in range(b_real)
                                     if not done[i]):
            rounds += 1
            positions = jnp.asarray(np.minimum(pos_np,
                                               self.max_length - 1))
            # draft proposal: repair the slot behind cur (idempotent — the
            # K/V write is a pure function of (token, position), and after
            # a fully-accepted window the draft never saw that token),
            # then chain spec_tokens greedy draft steps
            _, dcaches = draft._decode_jit(
                draft_raw, dcaches, prev,
                jnp.maximum(positions - 1, 0))
            window_cols = [cur]
            dcur = cur
            for j in range(self.spec_tokens):
                dlogits, dcaches = draft._decode_jit(
                    draft_raw, dcaches, dcur,
                    jnp.minimum(positions + j,
                                self.max_length - 1))
                dcur = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                window_cols.append(dcur)
            window = jnp.stack(window_cols, axis=1)  # (B, w)
            live = int((~done).sum())
            t_vf = time.time_ns() if tele else 0
            glogits, pools = self._verify_paged_jit(
                raw, self.pool.pools, tables, window, positions, limits)
            self.pool.pools = pools
            g = np.asarray(jnp.argmax(glogits, axis=-1))  # (B, w) host
            win = np.asarray(window)
            # accept the longest prefix the draft got right: window[j] is
            # committed iff it equals the target's own next token g[j-1]
            match = win[:, 1:] == g[:, :-1]               # (B, w-1)
            m = 1 + np.cumprod(match, axis=1).sum(axis=1)  # (B,) in [1, w]
            accepted_total = 0
            for i in range(b_real):
                if done[i]:
                    continue
                mi = int(m[i])
                accept_num[i] += mi - 1
                accept_den[i] += w - 1
                accepted_total += mi - 1
                for t_new in g[i, :mi]:
                    emitted[i].append(int(t_new))
                    if eos_id is not None and int(t_new) == eos_id:
                        done[i] = True
                        break
                if len(emitted[i]) >= max_new:
                    done[i] = True
            if tele:
                tele.event_deferred(
                    "serving.generate.verify", t_vf, time.time_ns(),
                    batch=batch, window=w, round=rounds,
                    accepted=accepted_total, proposed=live * (w - 1))
            # commit: cur' = g[m-1] at pos+m; prev' = the token at pos+m-1.
            # Rejected positions [pos+m, pos+w) keep reservation; their
            # stale K/V are overwritten before any read (paged.py doc).
            rows = np.arange(batch)
            new_cur = g[rows, np.minimum(m, w) - 1]
            new_prev = np.where(m >= 2, g[rows, np.maximum(m - 2, 0)],
                                np.asarray(cur))
            cur = jnp.asarray(new_cur.astype(np.int32))
            prev = jnp.asarray(new_prev.astype(np.int32))
            pos_np = pos_np + m
        if stats is not None:
            rates = [
                (float(accept_num[i] / accept_den[i])
                 if accept_den[i] else None)
                for i in range(b_real)]
            stats["draft_accept_rate"] = rates
            real = [r for r in rates if r is not None]
            stats["spec_accept_rate"] = (sum(real) / len(real)
                                         if real else None)
            stats["spec_rounds"] = rounds
        return [self._trim_row(emitted[i], max_new, eos_id)
                for i in range(b_real)]

    def _generate_contiguous(self, prompts, max_new_tokens: int, *,
                             temperature: float, key,
                             eos_id: Optional[int], trace: bool):
        """The r13 contiguous-cache engine (``paged=False``) — kept
        verbatim as the paged path's token-identity oracle."""
        tokens, lengths, b_real, lens = self._prep(prompts, max_new_tokens)
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        # deferred span emission (no registry lock in the decode loop —
        # it competes for the GIL with every other model's worker)
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])

        t_pf = time.time_ns() if tele else 0
        logits, caches = self._prefill_jit(raw, tokens, lengths)
        if tele:
            tele.event_deferred("serving.generate.prefill", t_pf,
                                time.time_ns(), batch=batch,
                                seq=int(tokens.shape[1]))
        positions = lengths  # where the sampled token goes
        steps = []
        key, sub = jax.random.split(key)
        cur = self._sample(logits, temperature, sub)
        for i in range(max_new_tokens):
            steps.append(cur)
            if i == max_new_tokens - 1:
                break
            t_dt = time.time_ns() if tele else 0
            logits, caches = self._decode_jit(raw, caches, cur,
                                              positions)
            if tele:
                tele.event_deferred("serving.generate.decode_token", t_dt,
                                    time.time_ns(), step=i + 1, batch=batch)
            positions = positions + 1
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        return self._trim(stacked, b_real, lens, max_new_tokens, eos_id)

    def generate_full_recompute(self, prompts: Sequence[Sequence[int]],
                                max_new_tokens: int = 16, *,
                                temperature: float = 0.0, key=None,
                                eos_id: Optional[int] = None
                                ) -> List[List[int]]:
        """O(T²) reference decode: re-prefill the whole grown sequence for
        every token. Exactly the same sampling stream as ``generate`` —
        the KV-cache paths (paged AND contiguous) must reproduce it
        token-for-token (greedy) — kept as the verification oracle, not a
        serving path."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        grown = [list(p) for p in prompts]
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        steps = []
        for i in range(max_new_tokens):
            tokens, lengths, b_real, _ = self._prep(grown, 1)
            logits, _ = self._prefill_jit(raw, tokens, lengths)
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
            steps.append(cur)
            host = np.asarray(cur)
            for r in range(len(grown)):
                grown[r].append(int(host[r]))
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        lens = [len(p) for p in prompts]
        return self._trim(stacked, len(prompts), lens, max_new_tokens,
                          eos_id)

    # -------------------------------------------------------------- health
    def health_probe(self) -> bool:
        """Finite-logits canary for the reload pipeline
        (docs/SERVING.md#resilience): one tiny prompt through the prefill
        executable; True iff every logit is finite. Runs at an
        already-warmed (smallest-bucket) signature, so on a warmed
        generator it never traces. The paged probe uses an all-trash page
        table — zero blocks reserved, the prompt attention never reads the
        pool — and first audits block-refcount CONSERVATION (plus trie
        consistency when the prefix cache is on), so a leak or
        double-free shows up in steady state, not at the next OOM."""
        b = int(self.policy.bucket_batch(1))
        t = self._prefill_len(1)
        tokens = jnp.ones((b, t), jnp.int32)
        lengths = jnp.ones((b,), jnp.int32)
        raw = self._raw_params()
        if self.paged:
            ok, detail = self.pool.conservation()
            if ok and self.cache is not None:
                # strict when idle: with no live streams the trie's holds
                # are the only legitimate holds, so any other allocated
                # block is a leaked stream ref
                ok, detail = self.cache.check(
                    strict_idle=(self.pool._streams == 0))
            check = ("serving.kv_pool_conservation"
                     + (f".{self.model_id}" if self.model_id else ""))
            tm.set_health(check, ok, detail)
            if not ok:
                return False
            tables = jnp.zeros((b, self.pool.max_blocks_per_stream),
                               jnp.int32)
            logits, pools = self._prefill_paged_jit(
                raw, self.pool.pools, tokens, lengths, tables)
            self.pool.pools = pools
        else:
            logits, _ = self._prefill_jit(raw, tokens, lengths)
        return bool(np.isfinite(np.asarray(logits)).all())

    # -------------------------------------------------------------- warmup
    def warmup(self, batch_sizes=None, prompt_lengths=None) -> int:
        """Pre-trace every (batch bucket × prefill bucket) prefill, every
        batch-bucket decode step, and — when speculating — every
        batch-bucket verify window and the draft's own programs, so
        steady-state serving never compiles (docs/SERVING.md). Defaults to
        the explicit bucket lists of the policy. Returns the number of
        signatures primed."""
        if batch_sizes is None:
            if not isinstance(self.policy.batch_buckets, tuple):
                raise ValueError("warmup() without batch_sizes needs "
                                 "explicit batch buckets")
            batch_sizes = self.policy.batch_buckets
        if prompt_lengths is None:
            if isinstance(self.policy.seq_buckets, tuple):
                # max_length is the implicit final bucket (_prefill_len)
                prompt_lengths = tuple(self.policy.seq_buckets) \
                    + (self.max_length,)
            else:
                # pow2 (the default policy): every pow2 prefill shape up to
                # max_length — log2(L) signatures, so router.load(kind=
                # "generate") on a conf without seq_buckets still boots
                prompt_lengths = tuple(
                    2 ** i for i in range(self.max_length.bit_length())
                ) + (self.max_length,)
        raw = self._raw_params()
        primed = 0
        # resume/chunk windows trace per (batch bucket, width): width is
        # the fixed chunk when configured, else the seq buckets (the
        # suffix-only window goes through the same bucketing)
        window = self.paged and (self.cache is not None
                                 or self.prefill_chunk is not None)
        if window and self.prefill_chunk is not None:
            window_widths = (min(self.prefill_chunk, self.max_length),)
        for b in batch_sizes:
            b = int(b)
            caches = None
            if self.paged:
                tables = jnp.zeros((b, self.pool.max_blocks_per_stream),
                                   jnp.int32)
            widths = sorted({min(int(t), self.max_length)
                             for t in prompt_lengths})
            for t in widths:
                tokens = jnp.zeros((b, t), jnp.int32)
                lengths = jnp.ones((b,), jnp.int32)
                if self.paged:
                    _, pools = self._prefill_paged_jit(
                        raw, self.pool.pools, tokens, lengths, tables)
                    self.pool.pools = pools
                else:
                    _, caches = self._prefill_jit(raw, tokens, lengths)
                primed += 1
            if window:
                for t in (window_widths if self.prefill_chunk is not None
                          else widths):
                    zi = jnp.zeros((b, t), jnp.int32)
                    z1 = jnp.zeros((b,), jnp.int32)
                    _, pools = self._prefill_window_jit(
                        raw, self.pool.pools, zi, zi, tables, z1, z1)
                    self.pool.pools = pools
                    primed += 1
            cur = jnp.zeros((b,), jnp.int32)
            pos = jnp.ones((b,), jnp.int32)
            if self.paged:
                limits = jnp.full((b,), self.max_length - 1, jnp.int32)
                _, pools = self._decode_paged_jit(
                    raw, self.pool.pools, tables, cur, pos, limits)
                self.pool.pools = pools
                primed += 1
                if self.draft is not None and self.spec_tokens > 0:
                    vwin = jnp.zeros((b, self.spec_tokens + 1), jnp.int32)
                    _, pools = self._verify_paged_jit(
                        raw, self.pool.pools, tables, vwin, pos, limits)
                    self.pool.pools = pools
                    primed += 1
            elif caches is not None:
                self._decode_jit(raw, caches, cur, pos)
                primed += 1
        if window:
            # the COW copy program: block ids are data, one signature ever
            z = jnp.asarray(0, jnp.int32)
            self.pool.pools = self._copy_block_jit(self.pool.pools, z, z)
            primed += 1
        if self.draft is not None:
            primed += self.draft.warmup(batch_sizes=batch_sizes,
                                        prompt_lengths=prompt_lengths)
        return primed

    # ---------------------------------------------------------------- stats
    def pool_stats(self) -> Optional[dict]:
        if self.pool is None:
            return None
        s = self.pool.stats()
        if self.cache is not None:
            s["prefix_cache"] = self.cache.stats()
        if self.prefill_chunk is not None:
            s["prefill_chunk"] = self.prefill_chunk
        return s

    def prefix_hit_rate(self) -> Optional[float]:
        """Lifetime radix-cache token hit rate, or None when the prefix
        cache is off. Surfaced top-level in ``ServingModel.describe()`` so
        the fleet router (serving/fleet.py) reads it from ``/v1/models``
        without digging through the pool stats tree."""
        if self.cache is None:
            return None
        return round(self.cache.hit_rate(), 4)
