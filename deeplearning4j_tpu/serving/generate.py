"""KV-cache autoregressive generation for the nlp/transformer stack.

The decode tier of the model server (docs/SERVING.md): a decoder-only LM
built from the native transformer layers (``BertEmbeddingLayer`` →
``TransformerEncoderBlock(causal=True)``× N → ``RnnOutputLayer``, e.g.
``zoo.bert.Bert(causal=True, task="mlm")``) is served with TWO compiled
programs instead of one quadratic recompute per token:

- **prefill**: one causal forward over the whole prompt, capturing every
  position's K/V into per-layer caches (``TransformerEncoderBlock.prefill``).
  Prompt lengths round up to the bucketing policy's ``seq_buckets`` — the
  decode-shape extension of ``data/bucketing.py``, so arbitrary prompt
  lengths reuse a small fixed set of prefill executables.
- **decode_step**: one token per call — embed at the row's position
  (``BertEmbeddingLayer.embed_step``), attend the single query over the
  cache (``TransformerEncoderBlock.decode_step``), project logits. One
  executable per batch bucket, every generated token reuses it.

Exactness contract (tests/test_serving.py): the cached K/V are computed by
the same ``_qkv`` projections as the full forward and written with
identity-preserving updates, so **greedy decode through the cache equals
greedy full-recompute decode token-for-token**. ``generate_full_recompute``
runs the O(T²) path for that proof (and as a reference implementation).

Both programs are plain ``jax.jit`` functions with trace markers, so the
CompileWatcher (and the ``serving.recompiles_total`` counter) sees every
signature they ever trace — steady-state serving shows 0.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import note_trace


class Generator:
    """Compile-once prefill/decode serving head over a decoder-only
    MultiLayerNetwork.

    ``batch_buckets`` / ``prefill_buckets`` default to the model conf's
    bucketing knobs (ONE policy source of truth with training and the
    classify tier); ``max_length`` defaults to the embedding layer's
    ``max_position`` and bounds prompt + generated tokens.
    """

    def __init__(self, net, *, max_length: Optional[int] = None,
                 batch_buckets=None, prefill_buckets=None):
        from deeplearning4j_tpu.nn.transformer import (BertEmbeddingLayer,
                                                       TransformerEncoderBlock)

        layers = net.layers
        if not layers or not isinstance(layers[0], BertEmbeddingLayer):
            raise ValueError("Generator needs a BertEmbeddingLayer input "
                             "(e.g. zoo.bert.Bert(causal=True, task='mlm'))")
        blocks = layers[1:-1]
        if not blocks or not all(isinstance(b, TransformerEncoderBlock)
                                 for b in blocks):
            raise ValueError("Generator needs TransformerEncoderBlock middle "
                             "layers")
        if not all(b.causal for b in blocks):
            raise ValueError("Generator needs causal=True blocks — a "
                             "bidirectional encoder cannot decode "
                             "autoregressively")
        if not hasattr(layers[-1], "_logits"):
            raise ValueError("Generator needs a per-token logits head "
                             "(RnnOutputLayer, task='mlm')")
        self.net = net
        self.emb = layers[0]
        self.blocks = list(blocks)
        self.head = layers[-1]
        self.max_length = int(max_length or self.emb.max_position)
        conf_policy = BucketingPolicy.from_conf(getattr(net, "conf", None))
        if batch_buckets is None and conf_policy is not None:
            batch_buckets = conf_policy.batch_buckets
        if prefill_buckets is None and conf_policy is not None:
            prefill_buckets = conf_policy.seq_buckets
        self.policy = BucketingPolicy(
            batch_buckets=batch_buckets or "pow2",
            seq_buckets=prefill_buckets or "pow2")
        self._prefill_jit = jax.jit(self._prefill)
        self._decode_jit = jax.jit(self._decode)

    # ------------------------------------------------------ traced programs
    def _prefill(self, params, tokens, lengths):
        """tokens (B, T) int32, lengths (B,) int32 → (next-token logits
        (B, V), caches). Padding rows/positions are masked out of every
        attention read; the cache rows they write are overwritten by
        generation before they are ever visible (nn/transformer.py)."""
        note_trace("serving.prefill", tokens, lengths)  # trace-time only
        b, t = tokens.shape
        x, _ = self.emb.apply(params[0], {}, tokens)
        pad_mask = (jnp.arange(t)[None, :]
                    < lengths[:, None]).astype(x.dtype)
        caches = []
        for i, blk in enumerate(self.blocks):
            cache = blk.init_cache(b, self.max_length, x.dtype)
            x, cache = blk.prefill(params[i + 1], x, cache, mask=pad_mask)
            caches.append(cache)
        h_last = x[jnp.arange(b), lengths - 1]
        logits = self.head._logits(params[-1], h_last)
        return logits, caches

    def _decode(self, params, caches, tokens, positions):
        """One autoregressive step: tokens (B,) placed at per-row
        ``positions`` (B,) → (next-token logits (B, V), caches)."""
        note_trace("serving.decode_step", tokens, positions)
        x = self.emb.embed_step(params[0], tokens, positions)[:, None, :]
        new_caches = []
        for i, blk in enumerate(self.blocks):
            x, cache = blk.decode_step(params[i + 1], x, caches[i], positions)
            new_caches.append(cache)
        logits = self.head._logits(params[-1], x[:, 0])
        return logits, new_caches

    # ------------------------------------------------------------- sampling
    @staticmethod
    def _sample(logits, temperature: float, key):
        if temperature and temperature > 0.0:
            return jax.random.categorical(
                key, logits / jnp.asarray(temperature, logits.dtype), axis=-1
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _prefill_len(self, longest: int) -> int:
        """Prefill shape for the longest prompt: its seq bucket, with
        ``max_length`` as the implicit FINAL bucket — a prompt above the
        largest explicit bucket pads up to max_length instead of tracing a
        fresh per-length executable (the pad-up-not-retrace contract,
        docs/SERVING.md; warmup() primes the max_length shape too)."""
        t = self.policy.bucket_seq(longest)
        top = self.policy.seq_buckets
        if isinstance(top, tuple) and longest > top[-1]:
            return self.max_length
        return min(t, self.max_length)

    def _prep(self, prompts: Sequence[Sequence[int]], max_new_tokens: int):
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({max(lens)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_length ({self.max_length})")
        b_real = len(prompts)
        b = self.policy.bucket_batch(b_real)
        t = self._prefill_len(max(lens))
        tokens = np.zeros((b, t), np.int32)
        lengths = np.ones((b,), np.int32)  # padded rows: 1 fake token
        for i, p in enumerate(prompts):
            tokens[i, :lens[i]] = np.asarray(p, np.int32)
            lengths[i] = lens[i]
        return (jnp.asarray(tokens), jnp.asarray(lengths), b_real, lens)

    def _trim(self, stacked, b_real: int, lens, max_new_tokens: int,
              eos_id: Optional[int]) -> List[List[int]]:
        out = []
        for i in range(b_real):
            row = [int(v) for v in stacked[i][:max_new_tokens]]
            if eos_id is not None and eos_id in row:
                row = row[: row.index(eos_id) + 1]
            out.append(row)
        return out

    # ------------------------------------------------------------ decoding
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 16, *, temperature: float = 0.0,
                 key=None, eos_id: Optional[int] = None,
                 trace: bool = False) -> List[List[int]]:
        """KV-cache decode: one prefill + ``max_new_tokens - 1`` decode
        steps, all on warmed executables. ``temperature=0`` is greedy
        (deterministic); otherwise categorical sampling from ``key``
        (default PRNGKey(0) — pass a key for fresh randomness).
        ``trace=True`` (a head-sampled serving batch) emits a prefill span
        and one ``serving.generate.decode_token`` span per generated
        position — the per-token ruler of
        docs/OBSERVABILITY.md#request-tracing--slos."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        tokens, lengths, b_real, lens = self._prep(prompts, max_new_tokens)
        params = self.net.params
        if key is None:
            key = jax.random.PRNGKey(0)
        # deferred span emission (no registry lock in the decode loop —
        # it competes for the GIL with every other model's worker)
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])

        t_pf = time.time_ns() if tele else 0
        logits, caches = self._prefill_jit(params, tokens, lengths)
        if tele:
            tele.event_deferred("serving.generate.prefill", t_pf,
                                time.time_ns(), batch=batch,
                                seq=int(tokens.shape[1]))
        positions = lengths  # where the sampled token goes
        steps = []
        key, sub = jax.random.split(key)
        cur = self._sample(logits, temperature, sub)
        for i in range(max_new_tokens):
            steps.append(cur)
            if i == max_new_tokens - 1:
                break
            t_dt = time.time_ns() if tele else 0
            logits, caches = self._decode_jit(params, caches, cur,
                                              positions)
            if tele:
                tele.event_deferred("serving.generate.decode_token", t_dt,
                                    time.time_ns(), step=i + 1, batch=batch)
            positions = positions + 1
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        return self._trim(stacked, b_real, lens, max_new_tokens, eos_id)

    def generate_full_recompute(self, prompts: Sequence[Sequence[int]],
                                max_new_tokens: int = 16, *,
                                temperature: float = 0.0, key=None,
                                eos_id: Optional[int] = None
                                ) -> List[List[int]]:
        """O(T²) reference decode: re-prefill the whole grown sequence for
        every token. Exactly the same sampling stream as ``generate`` —
        the KV-cache path must reproduce it token-for-token (greedy) —
        kept as the verification oracle, not a serving path."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        grown = [list(p) for p in prompts]
        params = self.net.params
        if key is None:
            key = jax.random.PRNGKey(0)
        steps = []
        for i in range(max_new_tokens):
            tokens, lengths, b_real, _ = self._prep(grown, 1)
            logits, _ = self._prefill_jit(params, tokens, lengths)
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
            steps.append(cur)
            host = np.asarray(cur)
            for r in range(len(grown)):
                grown[r].append(int(host[r]))
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        lens = [len(p) for p in prompts]
        return self._trim(stacked, len(prompts), lens, max_new_tokens,
                          eos_id)

    # -------------------------------------------------------------- health
    def health_probe(self) -> bool:
        """Finite-logits canary for the reload pipeline
        (docs/SERVING.md#resilience): one tiny prompt through the prefill
        executable; True iff every logit is finite. Runs at an
        already-warmed (smallest-bucket) signature, so on a warmed
        generator it never traces."""
        b = int(self.policy.bucket_batch(1))
        t = self._prefill_len(1)
        tokens = jnp.ones((b, t), jnp.int32)
        lengths = jnp.ones((b,), jnp.int32)
        logits, _ = self._prefill_jit(self.net.params, tokens, lengths)
        return bool(np.isfinite(np.asarray(logits)).all())

    # -------------------------------------------------------------- warmup
    def warmup(self, batch_sizes=None, prompt_lengths=None) -> int:
        """Pre-trace every (batch bucket × prefill bucket) prefill and every
        batch-bucket decode step, so steady-state serving never compiles
        (docs/SERVING.md). Defaults to the explicit bucket lists of the
        policy. Returns the number of signatures primed."""
        if batch_sizes is None:
            if not isinstance(self.policy.batch_buckets, tuple):
                raise ValueError("warmup() without batch_sizes needs "
                                 "explicit batch buckets")
            batch_sizes = self.policy.batch_buckets
        if prompt_lengths is None:
            if isinstance(self.policy.seq_buckets, tuple):
                # max_length is the implicit final bucket (_prefill_len)
                prompt_lengths = tuple(self.policy.seq_buckets) \
                    + (self.max_length,)
            else:
                # pow2 (the default policy): every pow2 prefill shape up to
                # max_length — log2(L) signatures, so router.load(kind=
                # "generate") on a conf without seq_buckets still boots
                prompt_lengths = tuple(
                    2 ** i for i in range(self.max_length.bit_length())
                ) + (self.max_length,)
        params = self.net.params
        primed = 0
        for b in batch_sizes:
            b = int(b)
            caches = None
            for t in sorted({min(int(t), self.max_length)
                             for t in prompt_lengths}):
                tokens = jnp.zeros((b, t), jnp.int32)
                lengths = jnp.ones((b,), jnp.int32)
                _, caches = self._prefill_jit(params, tokens, lengths)
                primed += 1
            if caches is not None:
                cur = jnp.zeros((b,), jnp.int32)
                pos = jnp.ones((b,), jnp.int32)
                self._decode_jit(params, caches, cur, pos)
                primed += 1
        return primed
