"""Planet-scale decode path: paged KV cache + speculative decoding + int8.

The decode tier of the model server (docs/SERVING.md#paged-kv--speculative-
decode): a decoder-only LM built from the native transformer layers
(``BertEmbeddingLayer`` → ``TransformerEncoderBlock(causal=True)`` × N →
``RnnOutputLayer``, e.g. ``zoo.bert.Bert(causal=True, task="mlm")``) served
by compile-once executables:

- **prefill** — one causal forward over the whole prompt. Prompt lengths
  round up to ``seq_buckets``; the prompt's K/V scatter into the paged
  block pool through each stream's page table (serving/paged.py).
- **decode_step** — one token per call over the page table: gather the
  stream's K/V rows out of the slot-flat pool, attend ``k_pos <=
  position``, scatter the new token's K/V at its slot. The page table is
  DATA, not shape, so ONE executable (per batch bucket) serves every mix
  of context lengths with zero steady-state recompiles — and the pool is
  shared, so memory scales with actual tokens, not ``streams ×
  max_length`` (the ``concurrent_streams_per_device`` headline).
- **verify** — the speculative-decoding window: a small DRAFT net
  (``Bert(causal=True)`` tiny, loaded per-model via the router) proposes
  ``spec_tokens`` greedy tokens one cheap step at a time; the TARGET
  verifies the whole window in ONE batched step through the paged cache
  and emits every leading token the draft got right plus one
  correction/bonus token from its own logits. Greedy speculative output
  is therefore TOKEN-IDENTICAL to greedy non-speculative output by
  construction — every emitted token is the target's own argmax —
  proven in tests/test_paged_decode.py including a draft that is always
  wrong (k rejections per round, still identical, just slower).
  Rejected tails roll back page-table state exactly: positions are host
  bookkeeping, and the stale K/V rows of rejected slots are provably
  overwritten before any read (serving/paged.py module doc).
  ``temperature > 0`` falls back to the plain per-token sampling loop —
  verify-consistent by construction (same program, same key stream as
  the non-speculative path).

Admission: a batch whose streams cannot all get blocks sheds with
:class:`~deeplearning4j_tpu.serving.resilience.PoolExhaustedError`
(HTTP 429 + Retry-After, flight-recorder cause ``pool_exhausted``)
BEFORE any device work; blocks free on completion/eos (the decode loop
exits early once every live row emitted eos) and on shed.

Weight-only int8 (serving/quantize.py): ``quantize="int8"`` stores
resident int8 weights + per-channel scales and dequantizes inside these
same executables; the fp32 path is bit-unchanged.

Exactness contracts (tests/test_paged_decode.py + tests/test_serving.py):
greedy decode through the paged cache == greedy decode through the
contiguous r13 cache == greedy O(T²) full recompute, token-for-token.
``generate_full_recompute`` remains the oracle. All programs are plain
``jax.jit`` with trace markers, so the CompileWatcher (and
``serving.recompiles_total``) sees every signature they ever trace.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.serving.paged import (BlockPool, PoolExhaustedError,
                                              default_pool_blocks)
from deeplearning4j_tpu.serving.quantize import maybe_quantize
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import note_trace


def _decoder_parts(net, what: str):
    """Validate and split a decoder-only MLN into (emb, blocks, head)."""
    from deeplearning4j_tpu.nn.transformer import (BertEmbeddingLayer,
                                                   TransformerEncoderBlock)

    layers = net.layers
    if not layers or not isinstance(layers[0], BertEmbeddingLayer):
        raise ValueError(f"{what} needs a BertEmbeddingLayer input "
                         "(e.g. zoo.bert.Bert(causal=True, task='mlm'))")
    blocks = layers[1:-1]
    if not blocks or not all(isinstance(b, TransformerEncoderBlock)
                             for b in blocks):
        raise ValueError(f"{what} needs TransformerEncoderBlock middle "
                         "layers")
    if not all(b.causal for b in blocks):
        raise ValueError(f"{what} needs causal=True blocks — a "
                         "bidirectional encoder cannot decode "
                         "autoregressively")
    if not hasattr(layers[-1], "_logits"):
        raise ValueError(f"{what} needs a per-token logits head "
                         "(RnnOutputLayer, task='mlm')")
    return layers[0], list(blocks), layers[-1]


class Generator:
    """Compile-once decode serving head over a decoder-only
    MultiLayerNetwork (module doc).

    ``batch_buckets`` / ``prefill_buckets`` default to the model conf's
    bucketing knobs (ONE policy source of truth with training and the
    classify tier); ``max_length`` defaults to the embedding layer's
    ``max_position`` and bounds prompt + generated tokens.

    Decode engine knobs: ``paged`` (default True — the r13 contiguous
    cache remains as ``paged=False``, the identity oracle), ``block_size``
    / ``pool_blocks`` (pool geometry; default pool holds the largest
    batch bucket at full context, so admission only bites when sized
    down deliberately), ``draft_net`` + ``spec_tokens`` (speculative
    decoding — the draft runs its own small contiguous cache), and
    ``quantize`` ("int8" weight-only serving)."""

    def __init__(self, net, *, max_length: Optional[int] = None,
                 batch_buckets=None, prefill_buckets=None,
                 paged: bool = True, block_size: int = 16,
                 pool_blocks: Optional[int] = None,
                 draft_net=None, spec_tokens: int = 4,
                 quantize: Optional[str] = None,
                 model_id: str = ""):
        self.emb, self.blocks, self.head = _decoder_parts(net, "Generator")
        self.net = net
        self.model_id = str(model_id)
        self.max_length = int(max_length or self.emb.max_position)
        conf_policy = BucketingPolicy.from_conf(getattr(net, "conf", None))
        if batch_buckets is None and conf_policy is not None:
            batch_buckets = conf_policy.batch_buckets
        if prefill_buckets is None and conf_policy is not None:
            prefill_buckets = conf_policy.seq_buckets
        self.policy = BucketingPolicy(
            batch_buckets=batch_buckets or "pow2",
            seq_buckets=prefill_buckets or "pow2")
        self.paged = bool(paged)
        self.block_size = int(block_size)
        self._qp = maybe_quantize(net, quantize, model_id=self.model_id)
        # contiguous programs: the paged=False engine, the full-recompute
        # oracle's prefill, and the draft substrate
        self._prefill_jit = jax.jit(self._prefill)
        self._decode_jit = jax.jit(self._decode)
        self.pool: Optional[BlockPool] = None
        if self.paged:
            # an AUTO-sized pool (pool_blocks=None) grows on demand
            # (_admit) instead of shedding — the r13 contiguous engine
            # never refused a batch for cache memory, and a dynamic
            # ("pow2") bucket policy has no largest batch to size for.
            # Admission control = the shed contract only applies when the
            # operator PINNED a budget.
            self._pool_auto = pool_blocks is None
            if pool_blocks is None:
                bb = self.policy.batch_buckets
                pool_blocks = default_pool_blocks(
                    bb if isinstance(bb, tuple) else (32,),
                    self.max_length, self.block_size)
            self.pool = BlockPool(self.blocks, block_size=self.block_size,
                                  num_blocks=int(pool_blocks),
                                  max_length=self.max_length,
                                  model_id=self.model_id)
            # pools are DONATED through the paged programs (the hot loop
            # must not copy the whole pool per token) — every call site
            # threads the returned pools back into self.pool.pools
            self._prefill_paged_jit = jax.jit(self._prefill_paged,
                                              donate_argnums=(1,))
            self._decode_paged_jit = jax.jit(self._decode_paged,
                                             donate_argnums=(1,))
            self._verify_paged_jit = jax.jit(self._verify_paged,
                                             donate_argnums=(1,))
        # speculative decoding: the draft is a plain contiguous-cache
        # generator over the (tiny) draft net — same bucket policy, so
        # draft prefill shapes always match the target's prep
        self.spec_tokens = int(spec_tokens)
        self.draft: Optional[Generator] = None
        if draft_net is not None:
            if not self.paged:
                raise ValueError("speculative decoding needs paged=True "
                                 "(the verify window is a paged program)")
            self.draft = Generator(
                draft_net, max_length=self.max_length,
                batch_buckets=self.policy.batch_buckets,
                prefill_buckets=self.policy.seq_buckets,
                paged=False, model_id=f"{self.model_id}/draft"
                if self.model_id else "")
            if self.draft.emb.max_position < self.max_length:
                raise ValueError(
                    f"draft net max_position {self.draft.emb.max_position} "
                    f"< target max_length {self.max_length}")

    # ----------------------------------------------------------- parameters
    def _raw_params(self):
        """What the traced programs take: the live fp32 tree (bit-unchanged
        legacy path) or the resident (int8 leaves, scales) pair."""
        if self._qp is None:
            return self.net.params
        return self._qp.args()

    def _params_of(self, raw):
        """Inside-jit: raw → the parameter tree the layers consume. For
        int8 this IS the in-forward dequantize (serving/quantize.py)."""
        if self._qp is None:
            return raw
        return self._qp.rebuild(raw)

    # ------------------------------------------------------ traced programs
    def _prefill(self, raw, tokens, lengths):
        """Contiguous-cache prefill: tokens (B, T) int32, lengths (B,)
        int32 → (next-token logits (B, V), caches). Padding rows/positions
        are masked out of every attention read; the cache rows they write
        are overwritten by generation before they are ever visible
        (nn/transformer.py)."""
        note_trace("serving.prefill", tokens, lengths)  # trace-time only
        params = self._params_of(raw)
        b, t = tokens.shape
        x, _ = self.emb.apply(params[0], {}, tokens)
        pad_mask = (jnp.arange(t)[None, :]
                    < lengths[:, None]).astype(x.dtype)
        caches = []
        for i, blk in enumerate(self.blocks):
            cache = blk.init_cache(b, self.max_length, x.dtype)
            x, cache = blk.prefill(params[i + 1], x, cache, mask=pad_mask)
            caches.append(cache)
        h_last = x[jnp.arange(b), lengths - 1]
        logits = self.head._logits(params[-1], h_last)
        return logits, caches

    def _decode(self, raw, caches, tokens, positions):
        """One contiguous-cache autoregressive step: tokens (B,) placed at
        per-row ``positions`` (B,) → (next-token logits (B, V), caches)."""
        note_trace("serving.decode_step", tokens, positions)
        params = self._params_of(raw)
        x = self.emb.embed_step(params[0], tokens, positions)[:, None, :]
        new_caches = []
        for i, blk in enumerate(self.blocks):
            x, cache = blk.decode_step(params[i + 1], x, caches[i], positions)
            new_caches.append(cache)
        logits = self.head._logits(params[-1], x[:, 0])
        return logits, new_caches

    def _slots_of(self, tables):
        """Page tables (B, max_blocks) → per-position flat slot indices
        (B, max_length). Sliced to EXACTLY max_length so the gathered
        layout — and therefore every attention reduction — has the same
        shape as the contiguous cache (the bit-level identity argument,
        ops/attention.paged_kv_gather)."""
        bs = self.block_size
        s = tables[:, :, None] * bs + jnp.arange(bs)[None, None, :]
        return s.reshape(tables.shape[0], -1)[:, :self.max_length]

    def _prefill_paged(self, raw, pools, tokens, lengths, tables):
        """Paged prefill: same causal forward as ``_prefill`` (the prompt
        attention runs over in-register K/V, so the logits are identical),
        with every position's K/V scattered through the page table."""
        note_trace("serving.prefill_paged", tokens, lengths)
        params = self._params_of(raw)
        b, t = tokens.shape
        x, _ = self.emb.apply(params[0], {}, tokens)
        pad_mask = (jnp.arange(t)[None, :]
                    < lengths[:, None]).astype(x.dtype)
        slots = self._slots_of(tables)[:, :t]
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.prefill_paged(params[i + 1], x, pools[i], slots,
                                        mask=pad_mask)
            new_pools.append(pool)
        h_last = x[jnp.arange(b), lengths - 1]
        logits = self.head._logits(params[-1], h_last)
        return logits, new_pools

    def _decode_paged(self, raw, pools, tables, tokens, positions, limits):
        """One paged autoregressive step (module doc). ``limits`` (B,) is
        each stream's last valid position — a row that finished while its
        batch keeps decoding redirects overrun writes to the trash block
        instead of clobbering a live slot."""
        note_trace("serving.decode_step_paged", tokens, positions)
        params = self._params_of(raw)
        x = self.emb.embed_step(params[0], tokens, positions)[:, None, :]
        slots = self._slots_of(tables)
        pos_w = positions[:, None]
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.decode_window_paged(params[i + 1], x, pools[i],
                                              slots, pos_w, limits=limits)
            new_pools.append(pool)
        logits = self.head._logits(params[-1], x[:, 0])
        return logits, new_pools

    def _verify_paged(self, raw, pools, tables, window, positions0, limits):
        """Speculative verify: ``window`` (B, W) tokens at positions
        ``positions0 + [0..W)`` → per-position next-token logits
        (B, W, V) in ONE batched step. Window K/V are written first, each
        query attends ``k_pos <= its position`` — exactly the sequential
        decode-step semantics, batched over the window."""
        note_trace("serving.verify_paged", window, positions0)
        params = self._params_of(raw)
        w = window.shape[1]
        pos_w = positions0[:, None] + jnp.arange(w)[None, :]
        x = self.emb.embed_window(params[0], window, pos_w)
        slots = self._slots_of(tables)
        new_pools = []
        for i, blk in enumerate(self.blocks):
            x, pool = blk.decode_window_paged(params[i + 1], x, pools[i],
                                              slots, pos_w, limits=limits)
            new_pools.append(pool)
        logits = self.head._logits(params[-1], x)
        return logits, new_pools

    # ------------------------------------------------------------- sampling
    @staticmethod
    def _sample(logits, temperature: float, key):
        if temperature and temperature > 0.0:
            return jax.random.categorical(
                key, logits / jnp.asarray(temperature, logits.dtype), axis=-1
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _prefill_len(self, longest: int) -> int:
        """Prefill shape for the longest prompt: its seq bucket, with
        ``max_length`` as the implicit FINAL bucket — a prompt above the
        largest explicit bucket pads up to max_length instead of tracing a
        fresh per-length executable (the pad-up-not-retrace contract,
        docs/SERVING.md; warmup() primes the max_length shape too)."""
        t = self.policy.bucket_seq(longest)
        top = self.policy.seq_buckets
        if isinstance(top, tuple) and longest > top[-1]:
            return self.max_length
        return min(t, self.max_length)

    def _prep(self, prompts: Sequence[Sequence[int]], max_new_tokens: int):
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_length:
            raise ValueError(
                f"prompt ({max(lens)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_length ({self.max_length})")
        b_real = len(prompts)
        b = self.policy.bucket_batch(b_real)
        t = self._prefill_len(max(lens))
        tokens = np.zeros((b, t), np.int32)
        lengths = np.ones((b,), np.int32)  # padded rows: 1 fake token
        for i, p in enumerate(prompts):
            tokens[i, :lens[i]] = np.asarray(p, np.int32)
            lengths[i] = lens[i]
        return (jnp.asarray(tokens), jnp.asarray(lengths), b_real, lens)

    @staticmethod
    def _trim_row(row: List[int], max_new: int,
                  eos_id: Optional[int]) -> List[int]:
        row = row[:max_new]
        if eos_id is not None and eos_id in row:
            row = row[: row.index(eos_id) + 1]
        return row

    def _trim(self, stacked, b_real: int, lens, max_new_tokens: int,
              eos_id: Optional[int]) -> List[List[int]]:
        return [self._trim_row([int(v) for v in stacked[i]],
                               max_new_tokens, eos_id)
                for i in range(b_real)]

    # ------------------------------------------------------------ admission
    def _admit(self, lens, max_new: int, batch: int):
        """Reserve every stream's blocks for the WHOLE generation —
        all-or-nothing (PoolExhaustedError → the scheduler's 429 shed) —
        and build the (B, max_blocks) page-table array. An AUTO-sized pool
        (no operator budget) GROWS to fit instead of shedding: reserve
        failed with nothing allocated and pool content never outlives a
        batch, so swapping in a larger pool is safe mid-flight."""
        counts = [self.pool.blocks_needed(l, max_new) for l in lens]
        try:
            tables_list = self.pool.reserve(counts)
        except PoolExhaustedError:
            if not self._pool_auto:
                raise
            # growth changes the pool shapes, so the NEXT paged calls
            # trace once at the new size — a capacity event, not steady
            # state (serving configs with finite buckets size the pool to
            # their largest batch up front and never reach this branch;
            # the 0-recompile contract is asserted there). Old buffers
            # are dropped BEFORE the new allocation so device residency
            # never doubles.
            need = int(sum(counts))
            grown = max(need, 2 * self.pool.num_blocks)
            tm.counter("serving.kv_pool_grown_total", model=self.model_id)
            tm.instant("serving.kv_pool_grown", model=self.model_id,
                       blocks=grown)
            old_peak = self.pool.peak_streams
            self.pool.pools = None  # free before the bigger alloc
            self.pool = BlockPool(self.blocks,
                                  block_size=self.block_size,
                                  num_blocks=grown,
                                  max_length=self.max_length,
                                  model_id=self.model_id)
            self.pool.peak_streams = old_peak
            tables_list = self.pool.reserve(counts)
        tables = jnp.asarray(self.pool.table_array(tables_list, batch))
        return tables_list, tables

    # ------------------------------------------------------------- decoding
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 16, *, temperature: float = 0.0,
                 key=None, eos_id: Optional[int] = None,
                 trace: bool = False,
                 stats: Optional[Dict] = None) -> List[List[int]]:
        """Decode ``prompts``: one prefill + per-token decode steps (or
        speculative verify windows when a draft net is attached and the
        decode is greedy), all on warmed executables. ``temperature=0`` is
        greedy (deterministic); otherwise categorical sampling from
        ``key`` (default PRNGKey(0)) through the plain per-token loop.
        ``trace=True`` (a head-sampled serving batch) emits prefill /
        ``decode_token`` / ``verify`` spans — the per-token ruler of
        docs/OBSERVABILITY.md#request-tracing--slos. ``stats`` (a dict,
        filled in place) receives ``draft_accept_rate`` per row and the
        batch ``spec_accept_rate`` when speculating."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        if not self.paged:
            return self._generate_contiguous(
                prompts, max_new_tokens, temperature=temperature, key=key,
                eos_id=eos_id, trace=trace)
        tokens, lengths, b_real, lens = self._prep(prompts, max_new_tokens)
        batch = int(tokens.shape[0])
        tables_list, tables = self._admit(lens, max_new_tokens, batch)
        try:
            speculate = (self.draft is not None and self.spec_tokens > 0
                         and not (temperature and temperature > 0.0))
            if speculate:
                return self._generate_speculative(
                    tokens, lengths, tables, b_real, lens, max_new_tokens,
                    eos_id=eos_id, trace=trace, stats=stats)
            return self._generate_paged(
                tokens, lengths, tables, b_real, lens, max_new_tokens,
                temperature=temperature, key=key, eos_id=eos_id,
                trace=trace)
        except BaseException:
            # a failure mid-decode may have consumed the donated pool
            # buffers — rebuild them (pool CONTENT never outlives a batch;
            # only the host allocator state matters, and release() below
            # restores that)
            self._reset_pools()
            raise
        finally:
            # blocks free on completion, eos early-exit, and shed alike
            self.pool.release(tables_list)

    def _reset_pools(self):
        self.pool.pools = [blk.init_pool(self.pool.num_slots)
                           for blk in self.blocks]

    def _generate_paged(self, tokens, lengths, tables, b_real, lens,
                        max_new: int, *, temperature: float, key,
                        eos_id: Optional[int], trace: bool):
        """The plain per-token paged loop (greedy or sampled) — the same
        sampling stream as the contiguous path, so paged==contiguous is
        token-exact (greedy) / stream-exact (sampled)."""
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])
        limits = jnp.asarray(np.asarray(
            [l + max_new - 1 for l in lens]
            + [0] * (batch - b_real), np.int32))

        t_pf = time.time_ns() if tele else 0
        logits, pools = self._prefill_paged_jit(raw, self.pool.pools,
                                                tokens, lengths, tables)
        self.pool.pools = pools
        if tele:
            tele.event_deferred("serving.generate.prefill", t_pf,
                                time.time_ns(), batch=batch,
                                seq=int(tokens.shape[1]), paged=True)
        positions = lengths
        steps = []
        done = np.zeros(b_real, bool)
        key, sub = jax.random.split(key)
        cur = self._sample(logits, temperature, sub)
        for i in range(max_new):
            steps.append(cur)
            if eos_id is not None:
                done |= (np.asarray(cur)[:b_real] == eos_id)
                if done.all():
                    break  # every live stream finished: free blocks early
            if i == max_new - 1:
                break
            t_dt = time.time_ns() if tele else 0
            logits, pools = self._decode_paged_jit(
                raw, self.pool.pools, tables, cur, positions, limits)
            self.pool.pools = pools
            if tele:
                tele.event_deferred("serving.generate.decode_token", t_dt,
                                    time.time_ns(), step=i + 1, batch=batch)
            positions = positions + 1
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        return self._trim(stacked, b_real, lens, max_new, eos_id)

    def _generate_speculative(self, tokens, lengths, tables, b_real, lens,
                              max_new: int, *, eos_id: Optional[int],
                              trace: bool, stats: Optional[Dict]):
        """Greedy speculative decode (module doc). Every emitted token is
        the TARGET's argmax — the draft only decides how many the verify
        window can commit at once."""
        raw = self._raw_params()
        draft = self.draft
        draft_raw = draft._raw_params()
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])
        w = self.spec_tokens + 1  # window = last accepted + k proposals
        limits_np = np.asarray([l + max_new - 1 for l in lens]
                               + [0] * (batch - b_real), np.int32)
        limits = jnp.asarray(limits_np)

        t_pf = time.time_ns() if tele else 0
        logits, pools = self._prefill_paged_jit(raw, self.pool.pools,
                                                tokens, lengths, tables)
        self.pool.pools = pools
        _, dcaches = draft._prefill_jit(draft_raw, tokens, lengths)
        if tele:
            tele.event_deferred("serving.generate.prefill", t_pf,
                                time.time_ns(), batch=batch,
                                seq=int(tokens.shape[1]), paged=True,
                                speculative=True)

        cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # token AT pos
        pos_np = np.asarray(lengths)  # cur's position, per row
        prev = tokens[jnp.arange(batch), jnp.asarray(pos_np) - 1]
        emitted: List[List[int]] = [[] for _ in range(batch)]
        done = np.zeros(b_real, bool)
        accept_num = np.zeros(batch, np.int64)
        accept_den = np.zeros(batch, np.int64)
        host_cur = np.asarray(cur)
        for i in range(b_real):
            emitted[i].append(int(host_cur[i]))
            if eos_id is not None and int(host_cur[i]) == eos_id:
                done[i] = True

        rounds = 0
        while not done.all() and any(len(emitted[i]) < max_new
                                     for i in range(b_real)
                                     if not done[i]):
            rounds += 1
            positions = jnp.asarray(np.minimum(pos_np,
                                               self.max_length - 1))
            # draft proposal: repair the slot behind cur (idempotent — the
            # K/V write is a pure function of (token, position), and after
            # a fully-accepted window the draft never saw that token),
            # then chain spec_tokens greedy draft steps
            _, dcaches = draft._decode_jit(
                draft_raw, dcaches, prev,
                jnp.maximum(positions - 1, 0))
            window_cols = [cur]
            dcur = cur
            for j in range(self.spec_tokens):
                dlogits, dcaches = draft._decode_jit(
                    draft_raw, dcaches, dcur,
                    jnp.minimum(positions + j,
                                self.max_length - 1))
                dcur = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                window_cols.append(dcur)
            window = jnp.stack(window_cols, axis=1)  # (B, w)
            live = int((~done).sum())
            t_vf = time.time_ns() if tele else 0
            glogits, pools = self._verify_paged_jit(
                raw, self.pool.pools, tables, window, positions, limits)
            self.pool.pools = pools
            g = np.asarray(jnp.argmax(glogits, axis=-1))  # (B, w) host
            win = np.asarray(window)
            # accept the longest prefix the draft got right: window[j] is
            # committed iff it equals the target's own next token g[j-1]
            match = win[:, 1:] == g[:, :-1]               # (B, w-1)
            m = 1 + np.cumprod(match, axis=1).sum(axis=1)  # (B,) in [1, w]
            accepted_total = 0
            for i in range(b_real):
                if done[i]:
                    continue
                mi = int(m[i])
                accept_num[i] += mi - 1
                accept_den[i] += w - 1
                accepted_total += mi - 1
                for t_new in g[i, :mi]:
                    emitted[i].append(int(t_new))
                    if eos_id is not None and int(t_new) == eos_id:
                        done[i] = True
                        break
                if len(emitted[i]) >= max_new:
                    done[i] = True
            if tele:
                tele.event_deferred(
                    "serving.generate.verify", t_vf, time.time_ns(),
                    batch=batch, window=w, round=rounds,
                    accepted=accepted_total, proposed=live * (w - 1))
            # commit: cur' = g[m-1] at pos+m; prev' = the token at pos+m-1.
            # Rejected positions [pos+m, pos+w) keep reservation; their
            # stale K/V are overwritten before any read (paged.py doc).
            rows = np.arange(batch)
            new_cur = g[rows, np.minimum(m, w) - 1]
            new_prev = np.where(m >= 2, g[rows, np.maximum(m - 2, 0)],
                                np.asarray(cur))
            cur = jnp.asarray(new_cur.astype(np.int32))
            prev = jnp.asarray(new_prev.astype(np.int32))
            pos_np = pos_np + m
        if stats is not None:
            rates = [
                (float(accept_num[i] / accept_den[i])
                 if accept_den[i] else None)
                for i in range(b_real)]
            stats["draft_accept_rate"] = rates
            real = [r for r in rates if r is not None]
            stats["spec_accept_rate"] = (sum(real) / len(real)
                                         if real else None)
            stats["spec_rounds"] = rounds
        return [self._trim_row(emitted[i], max_new, eos_id)
                for i in range(b_real)]

    def _generate_contiguous(self, prompts, max_new_tokens: int, *,
                             temperature: float, key,
                             eos_id: Optional[int], trace: bool):
        """The r13 contiguous-cache engine (``paged=False``) — kept
        verbatim as the paged path's token-identity oracle."""
        tokens, lengths, b_real, lens = self._prep(prompts, max_new_tokens)
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        # deferred span emission (no registry lock in the decode loop —
        # it competes for the GIL with every other model's worker)
        tele = tm.get_telemetry() if trace else None
        batch = int(tokens.shape[0])

        t_pf = time.time_ns() if tele else 0
        logits, caches = self._prefill_jit(raw, tokens, lengths)
        if tele:
            tele.event_deferred("serving.generate.prefill", t_pf,
                                time.time_ns(), batch=batch,
                                seq=int(tokens.shape[1]))
        positions = lengths  # where the sampled token goes
        steps = []
        key, sub = jax.random.split(key)
        cur = self._sample(logits, temperature, sub)
        for i in range(max_new_tokens):
            steps.append(cur)
            if i == max_new_tokens - 1:
                break
            t_dt = time.time_ns() if tele else 0
            logits, caches = self._decode_jit(raw, caches, cur,
                                              positions)
            if tele:
                tele.event_deferred("serving.generate.decode_token", t_dt,
                                    time.time_ns(), step=i + 1, batch=batch)
            positions = positions + 1
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        return self._trim(stacked, b_real, lens, max_new_tokens, eos_id)

    def generate_full_recompute(self, prompts: Sequence[Sequence[int]],
                                max_new_tokens: int = 16, *,
                                temperature: float = 0.0, key=None,
                                eos_id: Optional[int] = None
                                ) -> List[List[int]]:
        """O(T²) reference decode: re-prefill the whole grown sequence for
        every token. Exactly the same sampling stream as ``generate`` —
        the KV-cache paths (paged AND contiguous) must reproduce it
        token-for-token (greedy) — kept as the verification oracle, not a
        serving path."""
        if max_new_tokens < 1:
            return [[] for _ in prompts]
        grown = [list(p) for p in prompts]
        raw = self._raw_params()
        if key is None:
            key = jax.random.PRNGKey(0)
        steps = []
        for i in range(max_new_tokens):
            tokens, lengths, b_real, _ = self._prep(grown, 1)
            logits, _ = self._prefill_jit(raw, tokens, lengths)
            key, sub = jax.random.split(key)
            cur = self._sample(logits, temperature, sub)
            steps.append(cur)
            host = np.asarray(cur)
            for r in range(len(grown)):
                grown[r].append(int(host[r]))
        stacked = np.stack([np.asarray(s) for s in steps], axis=1)
        lens = [len(p) for p in prompts]
        return self._trim(stacked, len(prompts), lens, max_new_tokens,
                          eos_id)

    # -------------------------------------------------------------- health
    def health_probe(self) -> bool:
        """Finite-logits canary for the reload pipeline
        (docs/SERVING.md#resilience): one tiny prompt through the prefill
        executable; True iff every logit is finite. Runs at an
        already-warmed (smallest-bucket) signature, so on a warmed
        generator it never traces. The paged probe uses an all-trash page
        table — zero blocks reserved, the prompt attention never reads the
        pool."""
        b = int(self.policy.bucket_batch(1))
        t = self._prefill_len(1)
        tokens = jnp.ones((b, t), jnp.int32)
        lengths = jnp.ones((b,), jnp.int32)
        raw = self._raw_params()
        if self.paged:
            tables = jnp.zeros((b, self.pool.max_blocks_per_stream),
                               jnp.int32)
            logits, pools = self._prefill_paged_jit(
                raw, self.pool.pools, tokens, lengths, tables)
            self.pool.pools = pools
        else:
            logits, _ = self._prefill_jit(raw, tokens, lengths)
        return bool(np.isfinite(np.asarray(logits)).all())

    # -------------------------------------------------------------- warmup
    def warmup(self, batch_sizes=None, prompt_lengths=None) -> int:
        """Pre-trace every (batch bucket × prefill bucket) prefill, every
        batch-bucket decode step, and — when speculating — every
        batch-bucket verify window and the draft's own programs, so
        steady-state serving never compiles (docs/SERVING.md). Defaults to
        the explicit bucket lists of the policy. Returns the number of
        signatures primed."""
        if batch_sizes is None:
            if not isinstance(self.policy.batch_buckets, tuple):
                raise ValueError("warmup() without batch_sizes needs "
                                 "explicit batch buckets")
            batch_sizes = self.policy.batch_buckets
        if prompt_lengths is None:
            if isinstance(self.policy.seq_buckets, tuple):
                # max_length is the implicit final bucket (_prefill_len)
                prompt_lengths = tuple(self.policy.seq_buckets) \
                    + (self.max_length,)
            else:
                # pow2 (the default policy): every pow2 prefill shape up to
                # max_length — log2(L) signatures, so router.load(kind=
                # "generate") on a conf without seq_buckets still boots
                prompt_lengths = tuple(
                    2 ** i for i in range(self.max_length.bit_length())
                ) + (self.max_length,)
        raw = self._raw_params()
        primed = 0
        for b in batch_sizes:
            b = int(b)
            caches = None
            if self.paged:
                tables = jnp.zeros((b, self.pool.max_blocks_per_stream),
                                   jnp.int32)
            for t in sorted({min(int(t), self.max_length)
                             for t in prompt_lengths}):
                tokens = jnp.zeros((b, t), jnp.int32)
                lengths = jnp.ones((b,), jnp.int32)
                if self.paged:
                    _, pools = self._prefill_paged_jit(
                        raw, self.pool.pools, tokens, lengths, tables)
                    self.pool.pools = pools
                else:
                    _, caches = self._prefill_jit(raw, tokens, lengths)
                primed += 1
            cur = jnp.zeros((b,), jnp.int32)
            pos = jnp.ones((b,), jnp.int32)
            if self.paged:
                limits = jnp.full((b,), self.max_length - 1, jnp.int32)
                _, pools = self._decode_paged_jit(
                    raw, self.pool.pools, tables, cur, pos, limits)
                self.pool.pools = pools
                primed += 1
                if self.draft is not None and self.spec_tokens > 0:
                    window = jnp.zeros((b, self.spec_tokens + 1), jnp.int32)
                    _, pools = self._verify_paged_jit(
                        raw, self.pool.pools, tables, window, pos, limits)
                    self.pool.pools = pools
                    primed += 1
            elif caches is not None:
                self._decode_jit(raw, caches, cur, pos)
                primed += 1
        if self.draft is not None:
            primed += self.draft.warmup(batch_sizes=batch_sizes,
                                        prompt_lengths=prompt_lengths)
        return primed

    # ---------------------------------------------------------------- stats
    def pool_stats(self) -> Optional[dict]:
        return self.pool.stats() if self.pool is not None else None
