"""ModelServer — the HTTP front end of the serving tier.

A stdlib ``ThreadingHTTPServer`` (the ui_server pattern: no web framework,
no egress) over a :class:`~deeplearning4j_tpu.serving.router.ModelRouter`:

    POST /v1/models/<id>/infer     {"inputs": [[...], ...]}      → outputs
    POST /v1/models/<id>/generate  {"prompt_tokens"|"prompts": [[...], ...],
                                    "max_new_tokens": N,
                                    "temperature": T}            → tokens
    GET  /v1/models                                              → registry
    GET  /v1/models/<id>/debug/requests[?last=N]   flight-recorder dump
    GET  /metrics                  Prometheus text (ui_server collectors)
    GET  /healthz                  health JSON incl. serving + slo sections
    GET  /slo                      SLO evaluation JSON (util/slo.py)
    GET  /admin/status             worker identity: pid, worker_id, drain
    POST /admin/drain              begin graceful drain (idempotent, 200)

Connections are persistent: the handler speaks HTTP/1.1 with explicit
``Content-Length`` on every response, so a front tier (serving/fleet.py)
keeps one pooled connection per worker instead of paying a TCP handshake
per request. That is also why every POST path reads the full request body
*before* answering — an unread body would desynchronize the keep-alive
stream and corrupt the next request on the socket.

Request scope: every POST honors an inbound ``X-Request-Id`` header (or
mints one) and echoes it on the response — success AND error — so a caller
can correlate its 429 with the scheduler's flight-recorder record and the
sampled trace spans (docs/OBSERVABILITY.md#request-tracing--slos).

Request headers/body knobs: ``lane`` ("interactive"|"batch") and
``deadline_ms`` ride in the JSON body. The load-shed contract
(docs/SERVING.md): admission rejection and deadline misses answer **429**
with a ``Retry-After`` header; a draining server answers **503**; an
unknown model **404**; a malformed body **400**. Shedding is queue-depth
driven in the scheduler — the HTTP layer only translates.

Graceful drain reuses the r11 elastic seam: ``drain_signals`` (default
SIGTERM — what every preemption notice delivers) are trapped; on signal the
server stops admitting (503), finishes everything queued, counts
``serving.drains_total``, flips the ``serving.drained`` health check, and
``drained`` reads True — the same finish-in-flight → leave contract
``ElasticTrainer`` gives training (docs/FAULT_TOLERANCE.md).
"""

from __future__ import annotations

import json
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.serving.resilience import (ModelLoadError,
                                                   ReloadRejectedError)
from deeplearning4j_tpu.serving.router import ModelRouter, UnknownModelError
from deeplearning4j_tpu.serving.scheduler import ShedError
from deeplearning4j_tpu.util import telemetry as tm


class _ServingHTTPServer(ThreadingHTTPServer):
    # a connection burst wider than the stdlib default accept backlog (5)
    # must queue in the kernel, not get RST — admission control lives in
    # the scheduler's queue_limit, never in the TCP accept queue
    request_queue_size = 128
    daemon_threads = True


class ModelServer:
    """HTTP model server over a router (see module doc)."""

    def __init__(self, router: ModelRouter, port: int = 0,
                 host: str = "127.0.0.1",
                 drain_signals=(signal.SIGTERM,),
                 request_timeout_s: float = 60.0,
                 worker_id: Optional[str] = None):
        self.router = router
        self.host = host
        self.port = port
        self.drain_signals = tuple(drain_signals)
        self.request_timeout_s = float(request_timeout_s)
        #: fleet identity (serving/fleet.py spawns workers with one);
        #: surfaced on GET /admin/status so a supervisor can verify it is
        #: talking to the process it thinks it is after a respawn
        self.worker_id = worker_id
        self.drained = False
        self._draining = False
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._old_handlers: dict = {}

    # ----------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "ModelServer":
        if warmup:
            self.router.warmup()
        server = self
        handler = _make_handler(self)
        self._httpd = _ServingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolves port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="model-server")
        self._thread.start()
        self._install_signal_handlers()
        tm.set_health("serving.accepting", True,
                      f"listening on {self.host}:{self.port}")
        return server

    def _install_signal_handlers(self):
        try:
            for sig in self.drain_signals:
                self._old_handlers[sig] = signal.signal(
                    sig, self._on_drain_signal)
        except ValueError:
            # not the main thread (tests, embedded servers): drain stays
            # available through request_drain()
            self._old_handlers = {}

    def _restore_signal_handlers(self):
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._old_handlers = {}

    def _on_drain_signal(self, signum, frame):
        tm.counter("serving.drain_signals_total")
        self.request_drain()

    def request_drain(self, timeout: float = 30.0) -> "ModelServer":
        """Begin graceful drain (idempotent): stop admitting, finish queued
        work in the background, then report drained. Returns immediately;
        poll ``drained`` or join ``wait_drained()``."""
        if self._draining:
            return self
        self._draining = True
        tm.set_health("serving.accepting", False, "draining")

        def _drain():
            clean = self.router.drain(timeout=timeout)
            self.drained = True
            tm.set_health("serving.drained", True,
                          f"drained clean={clean}")

        threading.Thread(target=_drain, daemon=True,
                         name="serving-drain").start()
        return self

    def wait_drained(self, timeout: float = 30.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while not self.drained and time.monotonic() < deadline:
            time.sleep(0.01)
        return self.drained

    @property
    def draining(self) -> bool:
        return self._draining

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._restore_signal_handlers()
        self.router.shutdown()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------ handlers
    def _handle_infer(self, model_id: str, body: dict,
                      request_id: Optional[str] = None) -> dict:
        x = np.asarray(body["inputs"], np.float32)
        if x.ndim < 2:
            x = x[None]
        fut = self.router.submit(
            model_id, x, lane=body.get("lane", "interactive"),
            deadline_ms=body.get("deadline_ms"), request_id=request_id)
        out = fut.result(timeout=self.request_timeout_s)
        return {"model": model_id, "outputs": np.asarray(out).tolist()}

    def _handle_generate(self, model_id: str, body: dict,
                         request_id: Optional[str] = None) -> dict:
        prompts = body.get("prompt_tokens", body.get("prompts"))
        if prompts is None:
            raise ValueError("generate needs prompt_tokens")
        if prompts and isinstance(prompts[0], (int, float)):
            prompts = [prompts]  # single prompt shorthand
        opts = {"max_new_tokens": int(body.get("max_new_tokens", 16))}
        if body.get("temperature"):
            opts["temperature"] = float(body["temperature"])
        if body.get("eos_id") is not None:
            opts["eos_id"] = int(body["eos_id"])
        futs = []
        try:
            for i, p in enumerate(prompts):
                # multi-prompt bodies fan out to N scheduler requests: each
                # keeps the caller's id with a /row suffix, so all of them
                # correlate back to one HTTP request in the flight recorder
                rid = None if request_id is None else (
                    request_id if len(prompts) == 1
                    else f"{request_id}/{i}")
                futs.append(self.router.submit(
                    model_id, np.asarray(p, np.int32),
                    lane=body.get("lane", "batch"),
                    deadline_ms=body.get("deadline_ms"),
                    request_id=rid, **opts))
            toks = [f.result(timeout=self.request_timeout_s) for f in futs]
        except Exception:
            # a shed/timeout mid-list must not abandon live work: cancel
            # whatever is still queued (a no-op on finished futures) so an
            # overloaded model is not decoded-into for a 429'd request
            for f in futs:
                f.cancel()
            raise
        return {"model": model_id, "tokens": toks}


def _make_handler(server: ModelServer):
    from deeplearning4j_tpu.util.ui_server import UIServer

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 so connections persist across requests: the fleet front
        # tier (serving/fleet.py) pools one connection per worker. Every
        # response sets Content-Length (see _send), which 1.1 requires for
        # keep-alive framing.
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _send(self, status: int, body: bytes,
                  ctype: str = "application/json", headers=()):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, status: int, obj, headers=()):
            self._send(status, json.dumps(obj).encode(), headers=headers)

        def do_GET(self):
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            parts = u.path.strip("/").split("/")
            if u.path == "/metrics":
                self._send(200, UIServer._metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif u.path == "/healthz":
                body, ok = UIServer._healthz()
                self._send(200 if ok else 503, body.encode())
            elif u.path == "/slo":
                self._send(200, UIServer._slo_json().encode())
            elif u.path == "/admin/status":
                # worker identity for a fleet supervisor: cheap, never
                # touches the scheduler (a wedged model must not hide
                # the process's identity from its supervisor)
                import os

                self._send_json(200, {
                    "pid": os.getpid(),
                    "worker_id": server.worker_id,
                    "draining": server.draining,
                    "drained": server.drained,
                    "models": server.router.model_ids(),
                })
            elif u.path in ("/v1/models", "/v1/models/"):
                self._send_json(200, server.router.status())
            elif len(parts) == 5 and parts[:2] == ["v1", "models"] \
                    and parts[3:] == ["debug", "requests"]:
                # flight-recorder dump: the last-N completed/shed/error
                # request records for one model (docs/OBSERVABILITY.md)
                try:
                    last = int(parse_qs(u.query).get("last", [0])[0]) or None
                except ValueError:
                    last = None
                try:
                    records = server.router.debug_requests(parts[2],
                                                           last=last)
                except UnknownModelError as e:
                    self._send_json(404, {"error": f"unknown model {e}"})
                    return
                self._send_json(200, {"model": parts[2],
                                      "requests": records})
            else:
                self._send_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            # read the body FIRST, on every path — an unread body would
            # desynchronize the persistent (HTTP/1.1) connection and the
            # next request on the socket would parse garbage
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b""
            parts = self.path.strip("/").split("/")
            if parts == ["admin", "drain"]:
                # admin verb for a front tier / orchestrator that cannot
                # signal the process (adopted workers): same graceful
                # drain as SIGTERM, idempotent
                server.request_drain()
                self._send_json(200, {"draining": True})
                return
            # /v1/models/<id>/infer|generate|reload
            if len(parts) != 4 or parts[:2] != ["v1", "models"] \
                    or parts[3] not in ("infer", "generate", "reload"):
                self._send_json(404, {"error": f"no route {self.path}"})
                return
            model_id, verb = parts[2], parts[3]
            # honor the caller's X-Request-Id (mint one otherwise) and echo
            # it on EVERY response — 200s and sheds alike — so the caller,
            # the trace spans, and the flight recorder share one id
            from deeplearning4j_tpu.serving.scheduler import new_request_id

            rid = self.headers.get("X-Request-Id") or new_request_id()
            rid_hdr = [("X-Request-Id", rid)]
            if server.draining:
                self._send_json(
                    503, {"error": "draining", "model": model_id},
                    headers=[("Retry-After", "10")] + rid_hdr)
                return
            try:
                body = json.loads(raw or b"{}")
                if verb == "infer":
                    resp = server._handle_infer(model_id, body,
                                                request_id=rid)
                elif verb == "reload":
                    # rolling-reload admin verb (docs/SERVING.md#resilience)
                    resp = {"model": model_id,
                            "version": server.router.reload(
                                model_id, body["path"])}
                else:
                    resp = server._handle_generate(model_id, body,
                                                   request_id=rid)
                resp["request_id"] = rid
                self._send_json(200, resp, headers=rid_hdr)
            except UnknownModelError as e:
                self._send_json(404, {"error": f"unknown model {e}"},
                                headers=rid_hdr)
            except (ModelLoadError, ReloadRejectedError) as e:
                # a rejected reload is a CONFLICT with the live version,
                # which keeps serving — never a 5xx, the tier is healthy
                self._send_json(409, {"error": type(e).__name__,
                                      "detail": str(e)},
                                headers=rid_hdr)
            except ShedError as e:
                # the load-shed contract: 429 (or 503 while draining) with
                # Retry-After, body says why — docs/SERVING.md
                self._send_json(
                    e.http_status,
                    {"error": type(e).__name__, "detail": str(e),
                     "request_id": rid},
                    headers=[("Retry-After",
                              str(int(max(1, e.retry_after_s))))] + rid_hdr)
            except (KeyError, ValueError, TypeError) as e:
                self._send_json(400, {"error": f"bad request: {e!r}"},
                                headers=rid_hdr)
            except Exception as e:  # noqa: BLE001 — a broken batch must
                self._send_json(500, {"error": repr(e)},  # not kill the srv
                                headers=rid_hdr)

    return Handler
