"""Fleet worker entry point (serving/fleet.py spawns this):

    python -m deeplearning4j_tpu.serving.fleet_worker \\
        --spec spec.json --worker-id w0 --ready-file w0.ready.json

Replays the fleet spec (:func:`~deeplearning4j_tpu.serving.fleet.
fleet_spec`): restores each model's ModelSerializer archive, registers it
on a fresh :class:`ModelRouter`, starts a warmed :class:`ModelServer` on
an ephemeral port, and publishes ``{"port", "pid", "worker_id"}`` to the
ready file (atomic tmp + rename — the supervisor never reads a torn
handshake). The process then serves until SIGTERM, which runs the
server's graceful drain (finish queued work, 503 new admissions) before
exiting 0 — the same finish-in-flight contract the single-process tier
gives a preemption notice. A respawned worker with ``export_dir`` in its
``model_kw`` warms from the AOT export store instead of re-tracing
(docs/SERVING.md#fleet).

Spec ``env`` entries are applied before jax imports, so XLA thread
pinning (``XLA_FLAGS``) and ``DL4J_TPU_*`` knobs take effect in every
worker uniformly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_router(spec: dict):
    """A ModelRouter loaded per the fleet spec. Imported lazily so the
    ``--help`` path and the spec/env plumbing stay jax-free."""
    from deeplearning4j_tpu.data.bucketing import BucketingPolicy
    from deeplearning4j_tpu.serving.model import ServingModel
    from deeplearning4j_tpu.serving.router import ModelRouter
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    router = ModelRouter(name=spec.get("name", "fleet-worker"))
    for m in spec.get("models", []):
        net = ModelSerializer.restore_model(m["path"], load_updater=False)
        kw = dict(m.get("model_kw") or {})
        b = kw.get("bucketing")
        if isinstance(b, dict):
            kw["bucketing"] = BucketingPolicy(
                batch_buckets=tuple(b["batch_buckets"])
                if b.get("batch_buckets") else None,
                seq_buckets=tuple(b["seq_buckets"])
                if b.get("seq_buckets") else None)
        elif isinstance(b, str):
            kw["bucketing"] = BucketingPolicy.from_spec(b)
        if m.get("draft_path"):
            kw["draft_net"] = ModelSerializer.restore_model(
                m["draft_path"], load_updater=False)
        model = ServingModel(net, m["id"], kind=m.get("kind", "classify"),
                             quantize=m.get("quantize"), **kw)
        router.register(model, **(m.get("register") or {}))
    return router


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", required=True, help="fleet spec JSON path")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--ready-file", required=True,
                    help="where to publish {port,pid} once warmed")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    for k, v in (spec.get("env") or {}).items():
        # the supervisor already put these in our environment; honoring
        # them here too makes the module runnable by hand with the same
        # spec (setdefault: an explicit operator override wins)
        os.environ.setdefault(str(k), str(v))
    router = build_router(spec)
    from deeplearning4j_tpu.serving.server import ModelServer

    server = ModelServer(router, port=int(spec.get("port", 0)),
                         worker_id=args.worker_id).start(warmup=True)
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"port": server.port, "pid": os.getpid(),
                   "worker_id": args.worker_id, "host": server.host}, f)
    os.replace(tmp, args.ready_file)
    try:
        # serve until SIGTERM flips the drain flag (ModelServer installed
        # the handler — this IS the main thread) or the server dies
        while server._thread is not None and server._thread.is_alive():
            if server.draining:
                server.wait_drained(timeout=60.0)
                break
            time.sleep(0.2)
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
