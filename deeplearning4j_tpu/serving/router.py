"""Multi-model, multi-tenant routing for the serving tier.

One :class:`ModelRouter` owns a registry of model-id →
(:class:`~deeplearning4j_tpu.serving.model.ServingModel`,
:class:`~deeplearning4j_tpu.serving.scheduler.BatchScheduler`). Every model
gets its OWN scheduler — queue, lanes, admission limit, worker thread — so
tenant isolation is structural: one model's flood fills one queue and sheds
there; it cannot starve another model's priority lane
(tests/test_serving.py::test_multi_model_isolation).

Models register live (``register``) or load from a ModelSerializer archive
(``load`` — ``util/model_serializer.py``), and ``warmup()`` primes every
registered model's bucket executables through the r8 AOT export store
before the server accepts traffic.

The module keeps a registry of live routers so ``/healthz`` (ui_server)
and the telemetry default collectors can report serving state without the
probe importing the serving package (the same ``sys.modules`` guard the
elastic runtime uses).
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.serving.model import ServingModel
from deeplearning4j_tpu.serving.resilience import (BREAKER_STATES,  # noqa: F401 — re-export
                                                   ModelLoadError,
                                                   ReloadRejectedError)
from deeplearning4j_tpu.serving.scheduler import BatchScheduler
from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.health import record_anomaly

_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


class UnknownModelError(KeyError):
    """No such model-id (HTTP 404)."""

    http_status = 404


class ModelRouter:
    """model-id → (ServingModel, BatchScheduler) registry (see module doc)."""

    def __init__(self, name: str = "default"):
        self.name = name
        self._lock = threading.Lock()
        self._models: Dict[str, Tuple[ServingModel, BatchScheduler]] = {}
        self.draining = False
        self._reload_locks: Dict[str, threading.Lock] = {}
        self._watchers: Dict[str, Tuple[threading.Thread,
                                        threading.Event]] = {}
        _ROUTERS.add(self)

    # ------------------------------------------------------------ registry
    def register(self, model: ServingModel, *, max_wait_ms: float = 2.0,
                 max_batch: Optional[int] = None, queue_limit: int = 64,
                 start: bool = True, **sched_kw) -> BatchScheduler:
        """Attach a model under its ``model_id`` with its own scheduler
        (per-model admission control via ``queue_limit``; ``sched_kw``
        passes resilience knobs — ``breaker``, ``max_restarts``,
        ``supervised`` — through to :class:`BatchScheduler`)."""
        sched = BatchScheduler(model, max_wait_ms=max_wait_ms,
                               max_batch=max_batch, queue_limit=queue_limit,
                               **sched_kw)
        with self._lock:
            if model.model_id in self._models:
                raise ValueError(f"model {model.model_id!r} already "
                                 "registered")
            self._models[model.model_id] = (model, sched)
            self._reload_locks[model.model_id] = threading.Lock()
        tm.counter("serving.models_registered_total")
        if start:
            sched.start()
        return sched

    @staticmethod
    def _restore_archive(path: str, what: str):
        """Restore a ModelSerializer archive, wrapping every failure mode —
        missing file, truncated/corrupt zip, structure mismatch inside the
        archive — in ONE clean :class:`ModelLoadError` (the
        ``restore_latest_good`` corrupt-skip convention from
        util/checkpoint.py: a bad archive is a loud, typed error, never a
        half-initialized model)."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        try:
            return ModelSerializer.restore_model(path, load_updater=False)
        except ModelLoadError:
            raise
        except Exception as e:  # noqa: BLE001 — one typed error out
            raise ModelLoadError(
                f"{what}: archive {path!r} failed to load "
                f"({type(e).__name__}: {e})") from e

    def load(self, model_id: str, path: str, *, kind: str = "classify",
             quantize: Optional[str] = None,
             draft_path: Optional[str] = None,
             **model_kw) -> BatchScheduler:
        """Restore a ModelSerializer archive and register it. ``model_kw``
        passes through to :class:`ServingModel` (bucketing, export_dir,
        use_mesh, paged/pool knobs, …). A corrupt/truncated archive raises
        :class:`ModelLoadError` WITHOUT registering anything — the
        registry never holds a partially-loaded model.

        ``quantize="int8"`` serves weight-only int8: an int8 archive's
        stored quantization is adopted verbatim (bit-identical round
        trip); an fp32 archive is quantized at load
        (serving/quantize.py). ``draft_path`` loads a small draft net
        from its own archive and turns on speculative decoding for
        ``kind="generate"`` (serving/generate.py)."""
        net = self._restore_archive(path, f"load {model_id!r}")
        if draft_path is not None:
            model_kw["draft_net"] = self._restore_archive(
                draft_path, f"load {model_id!r} draft")
        model = ServingModel(net, model_id, kind=kind, quantize=quantize,
                             **model_kw)
        if quantize:
            try:
                archive_bytes = os.path.getsize(path)
                tm.gauge("serving.archive_bytes", archive_bytes,
                         model=model_id, quantize=str(quantize))
            except OSError:
                pass
        return self.register(model)

    # ------------------------------------------------------ rolling reload
    def _reject_reload(self, model_id: str, reason: str, detail: str):
        tm.counter("serving.reload_rejected_total", model=model_id,
                   reason=reason)
        record_anomaly("reload_rejected",
                       f"{model_id}: {reason}: {detail}"[:300],
                       source="serving", model=model_id)

    def reload(self, model_id: str, path: str, *, canary=None) -> int:
        """Rolling weight reload (docs/SERVING.md#resilience): load ``path``
        into a SHADOW model, warm every bucket signature on shadow state
        (the live model's caches are untouched), validate the new weights
        with a canary batch, then atomically swap between batch cycles —
        zero shed requests, zero steady-state recompiles, and the model's
        ``version`` advances. A corrupt archive (:class:`ModelLoadError`),
        a topology change, a warmup failure, or a NaN-producing canary
        (:class:`ReloadRejectedError`) leaves the OLD version serving,
        untouched. Returns the new version."""
        model, _sched = self.get(model_id)
        # register() created this lock with the model: a missing entry is a
        # broken invariant that must fail loudly, not silently hand each
        # caller a private lock (= no reload serialization at all)
        rlock = self._reload_locks[model_id]
        with rlock, tm.span("serving.reload", model=model_id):
            read_path, cleanup = path, None
            fault = fl.get_injector().fire(fl.RELOAD_CORRUPT_ARCHIVE)
            if fault is not None:
                # REAL mechanism: restore reads actually-truncated bytes —
                # the zip machinery fails exactly as it would on a torn
                # publish or a bad disk
                read_path = cleanup = self._truncated_copy(path, fault.arg)
            try:
                try:
                    new_net = self._restore_archive(
                        read_path, f"reload {model_id!r}")
                except ModelLoadError as e:
                    self._reject_reload(model_id, "load_error", str(e))
                    raise
            finally:
                if cleanup is not None:
                    try:
                        os.unlink(cleanup)
                    except OSError:
                        pass
            if not model.structure_matches(new_net):
                self._reject_reload(model_id, "structure_mismatch",
                                    "parameter tree differs from the live "
                                    "model — register a new model id")
                raise ReloadRejectedError(
                    f"reload {model_id!r}: archive {path!r} holds a "
                    "different topology; the live version keeps serving")
            try:
                shadow = model.clone_with_net(new_net)
                shadow.warmup()
            except Exception as e:  # noqa: BLE001 — reload must not crash
                self._reject_reload(model_id, "warmup_error", repr(e))
                raise ReloadRejectedError(
                    f"reload {model_id!r}: shadow warmup failed "
                    f"({type(e).__name__}: {e}); the live version keeps "
                    "serving") from e
            ok, detail = shadow.canary_check(canary)
            if not ok:
                self._reject_reload(model_id, "canary", detail)
                raise ReloadRejectedError(
                    f"reload {model_id!r}: canary rejected the new weights "
                    f"({detail}); the live version keeps serving")
            version = model.swap_from(shadow)
            tm.counter("serving.reloads_total", model=model_id)
            tm.instant("serving.reload_swap", model=model_id,
                       version=version, path=str(path))
            tm.set_health(f"serving.reload.{model_id}", True,
                          f"serving v{version} from {path}")
            return version

    @staticmethod
    def _truncated_copy(path: str, frac: Optional[float]) -> str:
        """The reload_corrupt_archive fault's mechanism: a copy of the
        archive truncated to ``frac`` (default 0.5) of its bytes."""
        with open(path, "rb") as f:
            data = f.read()
        cut = max(1, int(len(data) * (frac if frac else 0.5)))
        out = f"{path}.corrupt-{os.getpid()}"
        with open(out, "wb") as f:
            f.write(data[:cut])
        return out

    def watch(self, model_id: str, path: str,
              interval_s: float = 1.0) -> threading.Event:
        """Follow a published archive: a daemon poller reloads ``model_id``
        whenever ``path``'s (mtime, size) signature changes — the
        train-and-serve seam (parallel/elastic.py ``publish_archive``
        commits atomically via os.replace, so the poller never reads a
        torn file). A rejected reload is remembered by signature and not
        retried until the publisher commits again. Returns the stop event
        (also stopped by :meth:`unwatch` / :meth:`shutdown`)."""
        self.get(model_id)  # UnknownModelError before starting a thread
        stop = threading.Event()

        def _sig():
            try:
                st = os.stat(path)
                return (st.st_mtime_ns, st.st_size)
            except OSError:
                return None

        last_sig = _sig()  # reload only NEW commits, not the current file

        def _poll():
            nonlocal last_sig
            while not stop.wait(interval_s):
                sig = _sig()
                if sig is None or sig == last_sig:
                    continue
                try:
                    v = self.reload(model_id, path)
                    last_sig = sig
                    tm.counter("serving.watch_reloads_total",
                               model=model_id)
                    tm.instant("serving.watch_reload", model=model_id,
                               version=v)
                except (ModelLoadError, ReloadRejectedError,
                        UnknownModelError):
                    # already counted/anomaly-recorded by reload(); the
                    # old version keeps serving and this signature is
                    # remembered so a bad publish is not retry-spun
                    last_sig = sig
                    continue
                except Exception as e:  # noqa: BLE001 — poller survives
                    # an UNTYPED failure (transient fs error, OOM during
                    # shadow warmup) must be loud AND retried: the
                    # signature stays unconsumed so the next poll tries
                    # the same publish again instead of silently never
                    # serving good weights
                    tm.counter("serving.watch_errors_total",
                               model=model_id)
                    record_anomaly("watch_reload_error",
                                   f"{model_id}: {e!r}"[:200],
                                   source="serving", model=model_id)
                    continue

        t = threading.Thread(target=_poll, daemon=True,
                             name=f"serving-watch-{model_id}")
        with self._lock:
            old = self._watchers.pop(model_id, None)
            self._watchers[model_id] = (t, stop)
        if old is not None:
            old[1].set()
        t.start()
        return stop

    def unwatch(self, model_id: str) -> bool:
        with self._lock:
            entry = self._watchers.pop(model_id, None)
        if entry is None:
            return False
        entry[1].set()
        entry[0].join(timeout=30.0)
        return True

    def set_brownout(self, lanes=()):
        """Fan a brownout (resilience.BrownoutController) out to every
        model's scheduler; ``()`` restores full service."""
        for model_id in self.model_ids():
            _m, sched = self.get(model_id)
            sched.set_brownout(lanes)

    def get(self, model_id: str) -> Tuple[ServingModel, BatchScheduler]:
        with self._lock:
            entry = self._models.get(model_id)
        if entry is None:
            raise UnknownModelError(model_id)
        return entry

    def model_ids(self):
        with self._lock:
            return list(self._models)

    # ------------------------------------------------------------- serving
    def submit(self, model_id: str, payload, *, lane: str = "interactive",
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None, **opts):
        """Route one request to its model's scheduler; returns a Future.
        ``request_id`` (e.g. the HTTP layer's ``X-Request-Id``) threads
        through to the scheduler's trace spans and flight recorder."""
        _model, sched = self.get(model_id)
        return sched.submit(payload, lane=lane, deadline_ms=deadline_ms,
                            request_id=request_id, **opts)

    def debug_requests(self, model_id: str,
                       last: Optional[int] = None) -> list:
        """The model's flight-recorder ring (newest last) — the
        ``/v1/models/<id>/debug/requests`` body (docs/OBSERVABILITY.md)."""
        _model, sched = self.get(model_id)
        return sched.flight.dump(last=last)

    def warmup(self) -> int:
        """Prime every model's bucket executables (docs/SERVING.md).
        Returns total signatures compiled/loaded."""
        primed = 0
        for model_id in self.model_ids():
            model, _sched = self.get(model_id)
            with tm.span("serving.warmup", model=model_id):
                primed += model.warmup()
        return primed

    # ----------------------------------------------------------- lifecycle
    def _stop_watchers(self):
        with self._lock:
            watchers, self._watchers = dict(self._watchers), {}
        for _t, stop in watchers.values():
            stop.set()
        for t, _stop in watchers.values():
            # join so a reload in flight (shadow warmup is real XLA work)
            # finishes before teardown — a daemon thread dying mid-compile
            # aborts the process at interpreter exit
            t.join(timeout=30.0)

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain across every model (the SIGTERM path): stop
        admission everywhere, finish queued work, stop workers."""
        self.draining = True
        self._stop_watchers()
        ok = True
        for model_id in self.model_ids():
            _m, sched = self.get(model_id)
            ok = sched.drain(timeout=timeout) and ok
        tm.counter("serving.drains_total")
        tm.set_health("serving.drained", True,
                      f"router {self.name} drained (clean={ok})")
        return ok

    def shutdown(self):
        self.draining = True
        self._stop_watchers()
        for model_id in self.model_ids():
            _m, sched = self.get(model_id)
            sched.shutdown()

    # ---------------------------------------------------------------- stats
    def status(self) -> dict:
        out = {"draining": self.draining, "models": {}}
        for model_id in self.model_ids():
            model, sched = self.get(model_id)
            out["models"][model_id] = {**model.describe(), **sched.stats()}
        return out


def current_status() -> dict:
    """Serving section for /healthz (util/ui_server.py): every live
    router's per-model queue/latency/shed state. Empty dict when no router
    exists — the probe stays cheap."""
    routers = [r for r in list(_ROUTERS)]
    if not routers:
        return {}
    if len(routers) == 1:
        return routers[0].status()
    return {r.name: r.status() for r in routers}


def collect_metrics() -> list:
    """Scrape-time gauges for the telemetry default collectors: fresh
    queue depth / p50 / p99 / QPS per model (combined AND per-lane) even
    when no batch has run since the last scrape."""
    rows = []
    for r in list(_ROUTERS):
        for model_id in r.model_ids():
            try:
                _m, sched = r.get(model_id)
            except UnknownModelError:
                continue
            labels = {"model": model_id}
            rows.append(("serving.queue_depth", labels,
                         float(sched.queue_depth())))
            for lane, depth in sched.lane_queue_depths().items():
                rows.append(("serving.queue_depth",
                             {**labels, "lane": lane}, float(depth)))
            rows.append(("serving.qps_10s", labels, float(sched.qps())))
            rows.append(("serving.flight_recorder_depth", labels,
                         float(len(sched.flight))))
            # resilience surfaces (docs/SERVING.md#resilience): breaker
            # state (0 closed / 1 half-open / 2 open), reload version,
            # watchdog restarts — fresh at every scrape
            if sched.breaker is not None:
                rows.append(("serving.breaker_state", labels,
                             float(sched.breaker.state_value())))
            rows.append(("serving.model_version", labels,
                         float(_m.version)))
            rows.append(("serving.worker_restarts", labels,
                         float(sched._restarts)))
            for q, name in ((0.5, "serving.latency_p50_seconds"),
                            (0.99, "serving.latency_p99_seconds")):
                v = sched.latencies.quantile(q)
                if v is not None:
                    rows.append((name, labels, float(v)))
                for lane, win in sched.lane_latencies.items():
                    lv = win.quantile(q)
                    if lv is not None:
                        rows.append((name, {**labels, "lane": lane},
                                     float(lv)))
    return rows


def flight_snapshot(last: int = 64) -> dict:
    """Last-N flight-recorder records for every live router's models —
    the crash-dump serving section (util/stats.py), sys.modules-guarded
    at the call site like current_status()."""
    out: dict = {}
    for r in list(_ROUTERS):
        models = {}
        for model_id in r.model_ids():
            try:
                _m, sched = r.get(model_id)
            except UnknownModelError:
                continue
            records = sched.flight.dump(last=last)
            if records:
                models[model_id] = records
        if models:
            out[r.name] = models
    return out
