"""Multi-model, multi-tenant routing for the serving tier.

One :class:`ModelRouter` owns a registry of model-id →
(:class:`~deeplearning4j_tpu.serving.model.ServingModel`,
:class:`~deeplearning4j_tpu.serving.scheduler.BatchScheduler`). Every model
gets its OWN scheduler — queue, lanes, admission limit, worker thread — so
tenant isolation is structural: one model's flood fills one queue and sheds
there; it cannot starve another model's priority lane
(tests/test_serving.py::test_multi_model_isolation).

Models register live (``register``) or load from a ModelSerializer archive
(``load`` — ``util/model_serializer.py``), and ``warmup()`` primes every
registered model's bucket executables through the r8 AOT export store
before the server accepts traffic.

The module keeps a registry of live routers so ``/healthz`` (ui_server)
and the telemetry default collectors can report serving state without the
probe importing the serving package (the same ``sys.modules`` guard the
elastic runtime uses).
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.serving.model import ServingModel
from deeplearning4j_tpu.serving.scheduler import BatchScheduler
from deeplearning4j_tpu.util import telemetry as tm

_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


class UnknownModelError(KeyError):
    """No such model-id (HTTP 404)."""

    http_status = 404


class ModelRouter:
    """model-id → (ServingModel, BatchScheduler) registry (see module doc)."""

    def __init__(self, name: str = "default"):
        self.name = name
        self._lock = threading.Lock()
        self._models: Dict[str, Tuple[ServingModel, BatchScheduler]] = {}
        self.draining = False
        _ROUTERS.add(self)

    # ------------------------------------------------------------ registry
    def register(self, model: ServingModel, *, max_wait_ms: float = 2.0,
                 max_batch: Optional[int] = None, queue_limit: int = 64,
                 start: bool = True) -> BatchScheduler:
        """Attach a model under its ``model_id`` with its own scheduler
        (per-model admission control via ``queue_limit``)."""
        sched = BatchScheduler(model, max_wait_ms=max_wait_ms,
                               max_batch=max_batch, queue_limit=queue_limit)
        with self._lock:
            if model.model_id in self._models:
                raise ValueError(f"model {model.model_id!r} already "
                                 "registered")
            self._models[model.model_id] = (model, sched)
        tm.counter("serving.models_registered_total")
        if start:
            sched.start()
        return sched

    def load(self, model_id: str, path: str, *, kind: str = "classify",
             **model_kw) -> BatchScheduler:
        """Restore a ModelSerializer archive and register it. ``model_kw``
        passes through to :class:`ServingModel` (bucketing, export_dir,
        use_mesh, …)."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        net = ModelSerializer.restore_model(path, load_updater=False)
        model = ServingModel(net, model_id, kind=kind, **model_kw)
        return self.register(model)

    def get(self, model_id: str) -> Tuple[ServingModel, BatchScheduler]:
        with self._lock:
            entry = self._models.get(model_id)
        if entry is None:
            raise UnknownModelError(model_id)
        return entry

    def model_ids(self):
        with self._lock:
            return list(self._models)

    # ------------------------------------------------------------- serving
    def submit(self, model_id: str, payload, *, lane: str = "interactive",
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None, **opts):
        """Route one request to its model's scheduler; returns a Future.
        ``request_id`` (e.g. the HTTP layer's ``X-Request-Id``) threads
        through to the scheduler's trace spans and flight recorder."""
        _model, sched = self.get(model_id)
        return sched.submit(payload, lane=lane, deadline_ms=deadline_ms,
                            request_id=request_id, **opts)

    def debug_requests(self, model_id: str,
                       last: Optional[int] = None) -> list:
        """The model's flight-recorder ring (newest last) — the
        ``/v1/models/<id>/debug/requests`` body (docs/OBSERVABILITY.md)."""
        _model, sched = self.get(model_id)
        return sched.flight.dump(last=last)

    def warmup(self) -> int:
        """Prime every model's bucket executables (docs/SERVING.md).
        Returns total signatures compiled/loaded."""
        primed = 0
        for model_id in self.model_ids():
            model, _sched = self.get(model_id)
            with tm.span("serving.warmup", model=model_id):
                primed += model.warmup()
        return primed

    # ----------------------------------------------------------- lifecycle
    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain across every model (the SIGTERM path): stop
        admission everywhere, finish queued work, stop workers."""
        self.draining = True
        ok = True
        for model_id in self.model_ids():
            _m, sched = self.get(model_id)
            ok = sched.drain(timeout=timeout) and ok
        tm.counter("serving.drains_total")
        tm.set_health("serving.drained", True,
                      f"router {self.name} drained (clean={ok})")
        return ok

    def shutdown(self):
        self.draining = True
        for model_id in self.model_ids():
            _m, sched = self.get(model_id)
            sched.shutdown()

    # ---------------------------------------------------------------- stats
    def status(self) -> dict:
        out = {"draining": self.draining, "models": {}}
        for model_id in self.model_ids():
            model, sched = self.get(model_id)
            out["models"][model_id] = {**model.describe(), **sched.stats()}
        return out


def current_status() -> dict:
    """Serving section for /healthz (util/ui_server.py): every live
    router's per-model queue/latency/shed state. Empty dict when no router
    exists — the probe stays cheap."""
    routers = [r for r in list(_ROUTERS)]
    if not routers:
        return {}
    if len(routers) == 1:
        return routers[0].status()
    return {r.name: r.status() for r in routers}


def collect_metrics() -> list:
    """Scrape-time gauges for the telemetry default collectors: fresh
    queue depth / p50 / p99 / QPS per model (combined AND per-lane) even
    when no batch has run since the last scrape."""
    rows = []
    for r in list(_ROUTERS):
        for model_id in r.model_ids():
            try:
                _m, sched = r.get(model_id)
            except UnknownModelError:
                continue
            labels = {"model": model_id}
            rows.append(("serving.queue_depth", labels,
                         float(sched.queue_depth())))
            for lane, depth in sched.lane_queue_depths().items():
                rows.append(("serving.queue_depth",
                             {**labels, "lane": lane}, float(depth)))
            rows.append(("serving.qps_10s", labels, float(sched.qps())))
            rows.append(("serving.flight_recorder_depth", labels,
                         float(len(sched.flight))))
            for q, name in ((0.5, "serving.latency_p50_seconds"),
                            (0.99, "serving.latency_p99_seconds")):
                v = sched.latencies.quantile(q)
                if v is not None:
                    rows.append((name, labels, float(v)))
                for lane, win in sched.lane_latencies.items():
                    lv = win.quantile(q)
                    if lv is not None:
                        rows.append((name, {**labels, "lane": lane},
                                     float(lv)))
    return rows


def flight_snapshot(last: int = 64) -> dict:
    """Last-N flight-recorder records for every live router's models —
    the crash-dump serving section (util/stats.py), sys.modules-guarded
    at the call site like current_status()."""
    out: dict = {}
    for r in list(_ROUTERS):
        models = {}
        for model_id in r.model_ids():
            try:
                _m, sched = r.get(model_id)
            except UnknownModelError:
                continue
            records = sched.flight.dump(last=last)
            if records:
                models[model_id] = records
        if models:
            out[r.name] = models
    return out
