"""Continuous/dynamic batching scheduler with deadline-aware queues.

The request plane of the model server (docs/SERVING.md). Requests enter
per-priority-lane FIFO queues and a background worker coalesces them into
device batches:

- **lanes**: ``"interactive"`` drains strictly before ``"batch"`` — a bulk
  tenant's flood queues behind nothing the interactive lane needs (and
  every model gets its OWN scheduler via the router, so cross-model
  isolation is structural, not fair-queuing luck).
- **coalescing**: the first request opens a batch; the worker keeps
  admitting compatible requests (same lane, same per-request options)
  until the model's coalesce limit (the largest batch bucket) is reached
  or ``max_wait_ms`` has elapsed since the batch opened — classic
  max-batch/max-wait dynamic batching (ParallelInference.java's observable
  queue, grown up). The coalesced rows ride ``data/bucketing.py`` padding,
  so the batched output is BIT-identical to per-request output
  (tests/test_serving.py).
- **deadlines**: ``deadline_ms`` is the caller's queueing budget. A request
  still queued when it expires is shed with :class:`DeadlineExceededError`
  (the HTTP 429 path) instead of executing late — load-shedding work the
  caller has already given up on.
- **admission control**: a full queue rejects at submit time
  (:class:`QueueFullError`, HTTP 429 + Retry-After) — queue depth, not
  latency collapse, is the overload signal, and it feeds ``/healthz``.

Telemetry (all on the process registry → /metrics): per-model request/shed
counters, queue-depth gauge, batch-occupancy and latency histograms,
p50/p99 latency gauges, and ``serving.recompiles_total`` — the count of
XLA traces serving has caused since warmup, asserted 0 in steady state by
the CI smoke (benchmarks/serving_smoke.py).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.util import telemetry as tm

LANES = ("interactive", "batch")  # priority order, first drains first


class ShedError(RuntimeError):
    """Request rejected by load shedding (HTTP 429 + Retry-After)."""

    http_status = 429
    retry_after_s = 1.0


class QueueFullError(ShedError):
    """Admission control: the model's queue is at capacity."""


class DeadlineExceededError(ShedError):
    """The request's queueing deadline expired before execution started."""


class SchedulerDrainingError(ShedError):
    """The scheduler is draining (SIGTERM) — no new work accepted."""

    http_status = 503


@dataclasses.dataclass
class _Request:
    payload: Any
    rows: int
    future: Future
    lane: str
    opts_key: Tuple
    opts: Dict[str, Any]
    t_enqueue: float                 # monotonic
    deadline: Optional[float]        # absolute monotonic, or None


class _LatencyWindow:
    """Sliding window of recent request latencies for p50/p99 gauges (the
    telemetry histogram keeps the full Prometheus series; this gives exact
    quantiles over the recent past for /healthz and the bench)."""

    def __init__(self, size: int = 1024):
        self._buf = collections.deque(maxlen=size)
        self._lock = threading.Lock()

    def add(self, v: float):
        with self._lock:
            self._buf.append(v)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._buf:
                return None
            vals = sorted(self._buf)
        idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
        return vals[idx]


class BatchScheduler:
    """One model's request queue + coalescing worker (see module doc)."""

    def __init__(self, model, *, max_wait_ms: float = 2.0,
                 max_batch: Optional[int] = None, queue_limit: int = 64,
                 lanes=LANES):
        self.model = model
        self.model_id = model.model_id
        self.max_wait_ms = float(max_wait_ms)
        self.max_batch = int(max_batch or model.coalesce_limit())
        self.queue_limit = int(queue_limit)
        self.lanes = tuple(lanes)
        self._queues: Dict[str, collections.deque] = {
            lane: collections.deque() for lane in self.lanes}
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._accepting = True
        self._inflight = 0
        self.latencies = _LatencyWindow()
        self._completed_ts = collections.deque(maxlen=4096)
        self._ts_lock = threading.Lock()  # appends race /metrics scrapes
        self.counts = collections.Counter()  # completed/shed_* totals

    # ------------------------------------------------------------ admission
    def submit(self, payload, *, lane: str = "interactive",
               deadline_ms: Optional[float] = None, **opts) -> Future:
        """Enqueue one request; returns a Future of the model result.
        Raises a :class:`ShedError` subclass instead of queueing when the
        scheduler is draining or the queue is full."""
        if lane not in self._queues:
            raise ValueError(f"unknown lane {lane!r} (have {self.lanes})")
        rows = self.model.payload_rows(payload)
        now = time.monotonic()
        req = _Request(
            payload=payload, rows=rows, future=Future(), lane=lane,
            opts_key=tuple(sorted(opts.items())), opts=opts, t_enqueue=now,
            deadline=None if deadline_ms is None else now + deadline_ms / 1e3)
        with self._cv:
            if not self._accepting:
                self.counts["shed_draining"] += 1
                tm.counter("serving.shed_total", model=self.model_id,
                           reason="draining")
                raise SchedulerDrainingError(
                    f"{self.model_id}: scheduler draining")
            depth = sum(len(q) for q in self._queues.values())
            if depth >= self.queue_limit:
                self.counts["shed_queue_full"] += 1
                tm.counter("serving.shed_total", model=self.model_id,
                           reason="queue_full")
                raise QueueFullError(
                    f"{self.model_id}: queue at capacity ({depth})")
            self._queues[lane].append(req)
            tm.gauge("serving.queue_depth", depth + 1, model=self.model_id)
            self._cv.notify()
        tm.counter("serving.requests_total", model=self.model_id, lane=lane)
        return req.future

    # --------------------------------------------------------------- worker
    def start(self) -> "BatchScheduler":
        with self._cv:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"serving-{self.model_id}")
                self._thread.start()
        return self

    def _shed(self, req: _Request, exc: ShedError, reason: str):
        self.counts[f"shed_{reason}"] += 1
        tm.counter("serving.shed_total", model=self.model_id, reason=reason)
        if not req.future.set_running_or_notify_cancel():
            return
        req.future.set_exception(exc)

    def _sweep_expired_locked(self, now: float):
        for lane in self.lanes:
            q = self._queues[lane]
            kept = collections.deque()
            while q:
                req = q.popleft()
                if req.deadline is not None and now > req.deadline:
                    self._shed(req, DeadlineExceededError(
                        f"{self.model_id}: deadline expired after "
                        f"{(now - req.t_enqueue) * 1e3:.1f} ms in queue"),
                        "deadline")
                else:
                    kept.append(req)
            self._queues[lane] = q
            q.extend(kept)

    def _open_batch_locked(self) -> Optional[List[_Request]]:
        """Pop the head of the highest-priority non-empty lane."""
        for lane in self.lanes:
            if self._queues[lane]:
                return [self._queues[lane].popleft()]
        return None

    def _fill_batch_locked(self, batch: List[_Request]) -> int:
        """Admit compatible queued requests into the open batch (same lane
        first, then lower lanes — occupancy over strictness once the
        priority head is already in the batch). A request whose deadline
        expired while the batch was filling is shed here, not executed —
        the 429 contract holds even under a busy worker. Returns total
        rows."""
        head = batch[0]
        rows = sum(r.rows for r in batch)
        for lane in self.lanes:
            q = self._queues[lane]
            scan = len(q)
            for _ in range(scan):
                if rows >= self.max_batch:
                    return rows
                req = q[0]
                now = time.monotonic()
                if req.deadline is not None and now > req.deadline:
                    q.popleft()
                    self._shed(req, DeadlineExceededError(
                        f"{self.model_id}: deadline expired after "
                        f"{(now - req.t_enqueue) * 1e3:.1f} ms in queue"),
                        "deadline")
                    continue
                if req.opts_key != head.opts_key \
                        or rows + req.rows > self.max_batch:
                    break
                batch.append(q.popleft())
                rows += req.rows
        return rows

    def _loop(self):
        while True:
            with self._cv:
                while not self._stop \
                        and not any(self._queues[l] for l in self.lanes):
                    self._cv.wait(timeout=0.1)
                if self._stop \
                        and not any(self._queues[l] for l in self.lanes):
                    return
                self._sweep_expired_locked(time.monotonic())
                batch = self._open_batch_locked()
                if batch is None:
                    continue
                self._inflight = 1
            # max-wait window: keep admitting until the batch is full or
            # max_wait_ms has passed since it opened (continuous batching)
            t_open = time.monotonic()
            deadline = t_open + self.max_wait_ms / 1e3
            while True:
                with self._cv:
                    rows = self._fill_batch_locked(batch)
                    if rows >= self.max_batch:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
            try:
                self._run_batch(batch)
            finally:
                with self._cv:
                    self._inflight = 0
                    tm.gauge("serving.queue_depth",
                             sum(len(q) for q in self._queues.values()),
                             model=self.model_id)
                    self._cv.notify_all()

    def _run_batch(self, batch: List[_Request]):
        t0 = time.monotonic()
        with tm.span("serving.batch", model=self.model_id,
                     requests=len(batch), lane=batch[0].lane):
            try:
                results, stats = self.model.execute(
                    [r.payload for r in batch], **batch[0].opts)
            except Exception as e:  # a bad request fails its batch, never
                for req in batch:   # the worker (ParallelInference contract)
                    if req.future.set_running_or_notify_cancel():
                        req.future.set_exception(e)
                tm.counter("serving.batch_errors_total", model=self.model_id)
                return
        now = time.monotonic()
        for req, res in zip(batch, results):
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(res)
            lat = now - req.t_enqueue
            self.latencies.add(lat)
            with self._ts_lock:
                self._completed_ts.append(now)
            self.counts["completed"] += 1
            tm.observe("serving.request_latency_seconds", lat,
                       model=self.model_id, lane=req.lane)
        tm.counter("serving.batches_total", model=self.model_id)
        tm.counter("serving.recompiles_total", stats.get("recompiles", 0),
                   model=self.model_id)
        if stats.get("padded_rows"):
            tm.observe("serving.batch_occupancy",
                       stats["real_rows"] / stats["padded_rows"],
                       model=self.model_id)
        tm.observe("serving.batch_exec_seconds", now - t0,
                   model=self.model_id)
        for q, g in (("0.5", "serving.latency_p50_seconds"),
                     ("0.99", "serving.latency_p99_seconds")):
            val = self.latencies.quantile(float(q))
            if val is not None:
                tm.gauge(g, val, model=self.model_id)

    # ----------------------------------------------------------- lifecycle
    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain (the r11 SIGTERM seam, serving-side): stop
        accepting, FINISH everything already queued, then stop the worker.
        Returns True when the queues emptied within ``timeout``."""
        with self._cv:
            self._accepting = False
            self._cv.notify_all()
        deadline = time.monotonic() + timeout
        with self._cv:
            while (any(self._queues[l] for l in self.lanes)
                   or self._inflight) and time.monotonic() < deadline:
                self._cv.wait(timeout=0.1)
            drained = not any(self._queues[l] for l in self.lanes) \
                and not self._inflight
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return drained

    def shutdown(self):
        """Immediate stop: fail everything still queued."""
        with self._cv:
            self._accepting = False
            self._stop = True
            pending = [r for l in self.lanes for r in self._queues[l]]
            for l in self.lanes:
                self._queues[l].clear()
            self._cv.notify_all()
        for req in pending:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(
                    SchedulerDrainingError(f"{self.model_id}: shut down"))
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ---------------------------------------------------------------- stats
    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def qps(self, window_s: float = 10.0) -> float:
        now = time.monotonic()
        with self._ts_lock:
            n = sum(1 for t in self._completed_ts if now - t <= window_s)
        return n / window_s

    def stats(self) -> dict:
        p50 = self.latencies.quantile(0.5)
        p99 = self.latencies.quantile(0.99)
        return {
            "queue_depth": self.queue_depth(),
            "accepting": self._accepting,
            "completed": self.counts["completed"],
            "shed": {k[len("shed_"):]: v for k, v in self.counts.items()
                     if k.startswith("shed_")},
            "qps_10s": round(self.qps(), 3),
            "latency_p50_ms": None if p50 is None else round(p50 * 1e3, 3),
            "latency_p99_ms": None if p99 is None else round(p99 * 1e3, 3),
            "max_batch": self.max_batch,
            "max_wait_ms": self.max_wait_ms,
            "queue_limit": self.queue_limit,
        }
