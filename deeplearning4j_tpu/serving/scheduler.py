"""Continuous/dynamic batching scheduler with deadline-aware queues.

The request plane of the model server (docs/SERVING.md). Requests enter
per-priority-lane FIFO queues and a background worker coalesces them into
device batches:

- **lanes**: ``"interactive"`` drains strictly before ``"batch"`` — a bulk
  tenant's flood queues behind nothing the interactive lane needs (and
  every model gets its OWN scheduler via the router, so cross-model
  isolation is structural, not fair-queuing luck).
- **coalescing**: the first request opens a batch; the worker keeps
  admitting compatible requests (same lane, same per-request options)
  until the model's coalesce limit (the largest batch bucket) is reached
  or ``max_wait_ms`` has elapsed since the batch opened — classic
  max-batch/max-wait dynamic batching (ParallelInference.java's observable
  queue, grown up). The coalesced rows ride ``data/bucketing.py`` padding,
  so the batched output is BIT-identical to per-request output
  (tests/test_serving.py).
- **deadlines**: ``deadline_ms`` is the caller's queueing budget. A request
  still queued when it expires is shed with :class:`DeadlineExceededError`
  (the HTTP 429 path) instead of executing late — load-shedding work the
  caller has already given up on.
- **admission control**: a full queue rejects at submit time
  (:class:`QueueFullError`, HTTP 429 + Retry-After) — queue depth, not
  latency collapse, is the overload signal, and it feeds ``/healthz``.

Telemetry (all on the process registry → /metrics): per-model request/shed
counters, queue-depth gauge, batch-occupancy and latency histograms,
p50/p99 latency gauges (combined AND split by ``lane``), and
``serving.recompiles_total`` — the count of XLA traces serving has caused
since warmup, asserted 0 in steady state by the CI smoke
(benchmarks/serving_smoke.py).

Request-scope observability (docs/OBSERVABILITY.md#request-tracing--slos):
every request carries a ``request_id`` (the HTTP layer honors/echoes
``X-Request-Id``) and wall-clock phase stamps — queue wait, batch-fill
wait, device compute — emitted as telemetry spans on the shared trace
timebase when the request is **head-sampled** (``DL4J_TPU_TRACE_SAMPLE``,
a 0..1 keep fraction; slow/shed/error requests are ALWAYS kept so the
interesting tail never depends on the dice; ``0`` disables request tracing
entirely). Every completed/shed/errored request additionally lands in the
:class:`FlightRecorder` — a bounded per-model ring dumpable via
``/v1/models/<id>/debug/requests`` and appended to the crash dump — so a
postmortem after a shed storm has the last N requests in hand regardless
of sampling.
"""

from __future__ import annotations

import bisect
import collections
import itertools
import dataclasses
import os
import random
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.serving.resilience import (BrownoutShedError,
                                                   CircuitBreaker,
                                                   CircuitOpenError,
                                                   DeadlineExceededError,
                                                   PoolExhaustedError,
                                                   QueueFullError,
                                                   SchedulerDrainingError,
                                                   SchedulerStoppedError,
                                                   ShedError,
                                                   WorkerCrashedError)
from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.faults import RetryPolicy
from deeplearning4j_tpu.util.health import record_anomaly

LANES = ("interactive", "batch")  # priority order, first drains first

#: default watchdog backoff between worker restarts (serving workers are
#: cheap to restart; the deadline bounds a crash-looping model's thrash)
WORKER_RESTART_POLICY = RetryPolicy(max_attempts=8, base_delay=0.05,
                                    max_delay=2.0, jitter=0.25)

#: head-sampling keep fraction when DL4J_TPU_TRACE_SAMPLE is unset: 2% of
#: healthy requests get full phase spans; slow/shed/error requests are
#: always kept (see trace_sample_rate); the flight recorder sees 100%.
DEFAULT_TRACE_SAMPLE = 0.02

#: a completed request slower than this is "slow" and always traced
SLOW_REQUEST_MS = 100.0

_sample_cache: Tuple[Optional[str], float] = ("\x00unset", DEFAULT_TRACE_SAMPLE)


def trace_sample_rate() -> float:
    """The head-sampling keep fraction (0..1) from ``DL4J_TPU_TRACE_SAMPLE``
    (parse memoized on the raw string — submit() calls this per request).
    ``0`` means request tracing is OFF, including the slow/shed/error
    always-keep; unset means :data:`DEFAULT_TRACE_SAMPLE`."""
    global _sample_cache
    raw = os.environ.get("DL4J_TPU_TRACE_SAMPLE")
    if raw == _sample_cache[0]:
        return _sample_cache[1]
    try:
        val = min(1.0, max(0.0, float(raw)))  # type: ignore[arg-type]
    except (TypeError, ValueError):
        val = DEFAULT_TRACE_SAMPLE
    _sample_cache = (raw, val)
    return val


_id_counter = itertools.count()
_id_prefix = f"{random.getrandbits(24):06x}"  # per-process, import-time


def new_request_id() -> str:
    """Cheap process-unique 12-hex request id: random per-process prefix
    + monotone counter. NOT uuid4 — its os.urandom syscall drops the GIL
    and re-acquiring behind a busy scheduler worker measured ~100µs per
    submit() on the mixed serving bench (a 30% QPS regression)."""
    return f"{_id_prefix}{next(_id_counter) & 0xFFFFFF:06x}"


#: staged-trace bound per scheduler (sampled requests awaiting export)
_TRACE_STAGE_MAX = 4096

#: every live scheduler, for export-time span materialization
#: (telemetry._fold_pending -> collect_deferred_spans, sys.modules-guarded
#: exactly like the serving metrics collector)
_SCHEDULERS: "weakref.WeakSet" = weakref.WeakSet()


def collect_deferred_spans() -> List[dict]:
    """Materialize every live scheduler's staged request phase spans into
    Chrome-event dicts and clear the staging lists. Called by telemetry at
    export time (chrome_trace/drain_events/snapshot) — per-request span
    emission on the worker thread measured ~20µs/event of GIL stolen from
    other models' decode loops, so the hot path stages one tuple instead
    and ALL dict building happens here, on the cold export path."""
    out: List[dict] = []
    for s in list(_SCHEDULERS):
        try:
            out.extend(s._materialize_spans())
        except Exception:
            continue  # a dying scheduler must never break an export
    return out


class FlightRecorder:
    """Bounded ring of per-request postmortem records (one per completed,
    shed, or errored request — independent of trace sampling). Record
    schema: ``id, lane, rows, bucket, status(ok|shed|error), cause,
    queue_ms, fill_ms, compute_ms, total_ms, tokens_per_sec?, sampled,
    traced, time`` (docs/OBSERVABILITY.md#flight-recorder)."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def record(self, rec: dict):
        with self._lock:
            self._buf.append(rec)

    def dump(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._buf)
        if last is not None and last > 0:
            out = out[-last:]
        return out

    def __len__(self):
        with self._lock:
            return len(self._buf)


# the shed-error hierarchy lives in serving/resilience.py (ISSUE 13) and is
# re-exported here so every pre-existing `from ...scheduler import ShedError`
# import path keeps working
__all_errors__ = (ShedError, QueueFullError, DeadlineExceededError,
                  PoolExhaustedError, SchedulerDrainingError,
                  SchedulerStoppedError, CircuitOpenError,
                  BrownoutShedError, WorkerCrashedError)


@dataclasses.dataclass
class _Request:
    payload: Any
    rows: int
    future: Future
    lane: str
    opts_key: Tuple
    opts: Dict[str, Any]
    t_enqueue: float                 # monotonic
    deadline: Optional[float]        # absolute monotonic, or None
    request_id: str = ""
    sampled: bool = False            # head-sampling decision at submit
    # wall-clock phase stamps (ns) for span emission + the flight recorder:
    # submit -> joined a batch -> execute started -> execute done
    t_submit_ns: int = 0
    t_open_ns: int = 0
    t_exec0_ns: int = 0
    t_exec1_ns: int = 0


class _LatencyWindow:
    """Sliding window of recent request latencies for p50/p99 gauges (the
    telemetry histogram keeps the full Prometheus series; this gives exact
    quantiles over the recent past for /healthz and the bench)."""

    def __init__(self, size: int = 1024):
        self._buf = collections.deque(maxlen=size)
        self._sorted: List[float] = []
        self._lock = threading.Lock()

    def add(self, v: float):
        # the sorted view is maintained INCREMENTALLY (one C-speed insort
        # per add, one bisect-delete per eviction): the batch tail reads
        # p50/p99 on every window it touched, and a full sort there was
        # ~50µs of GIL per call — measured stealing 2-3x wall from the
        # OTHER model's per-token decode loop on the mixed serving bench
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                evicted = self._buf[0]
                i = bisect.bisect_left(self._sorted, evicted)
                del self._sorted[i]
            self._buf.append(v)
            bisect.insort(self._sorted, v)

    def quantile(self, q: float) -> Optional[float]:
        return self.quantiles((q,))[0]

    def quantiles(self, qs) -> tuple:
        """Several quantiles in one locked read (no sort — see add)."""
        with self._lock:
            if not self._sorted:
                return tuple(None for _ in qs)
            n = len(self._sorted) - 1
            return tuple(
                self._sorted[min(n, max(0, int(round(q * n))))] for q in qs)


class BatchScheduler:
    """One model's request queue + coalescing worker (see module doc)."""

    def __init__(self, model, *, max_wait_ms: float = 2.0,
                 max_batch: Optional[int] = None, queue_limit: int = 64,
                 lanes=LANES, flight_capacity: int = 256,
                 breaker="default", max_restarts: int = 3,
                 restart_policy: Optional[RetryPolicy] = None,
                 restart_reset_batches: int = 100,
                 supervised: bool = True):
        self.model = model
        self.model_id = model.model_id
        self.max_wait_ms = float(max_wait_ms)
        self.max_batch = int(max_batch or model.coalesce_limit())
        self.queue_limit = int(queue_limit)
        self.lanes = tuple(lanes)
        #: per-model circuit breaker (serving/resilience.py); pass
        #: ``breaker=None`` to disable, or a configured CircuitBreaker
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(model_id=self.model_id)
            if breaker == "default" else breaker)
        #: watchdog budget: worker restarts before the scheduler is declared
        #: dead (health check flips, queued futures fail loudly). The budget
        #: bounds a CRASH LOOP, not lifetime crashes: after
        #: ``restart_reset_batches`` clean batches since the last crash the
        #: spent budget resets — a rare transient (one device OOM a day)
        #: must not accumulate over weeks into a permanent 503
        self.max_restarts = int(max_restarts)
        self.restart_policy = restart_policy or WORKER_RESTART_POLICY
        self.restart_reset_batches = int(restart_reset_batches)
        self.supervised = bool(supervised)
        self._restarts = 0
        self._batches_since_crash = 0
        self._worker_dead = False
        self._batch_seq = 0            # batch-cycle counter (fault @step)
        self._current_batch: Optional[List[_Request]] = None
        self._brownout_lanes: frozenset = frozenset()
        self._queues: Dict[str, collections.deque] = {
            lane: collections.deque() for lane in self.lanes}
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._accepting = True
        self._inflight = 0
        self.latencies = _LatencyWindow()
        self.lane_latencies: Dict[str, _LatencyWindow] = {
            lane: _LatencyWindow() for lane in self.lanes}
        self._completed_ts = collections.deque(maxlen=4096)
        self._ts_lock = threading.Lock()  # appends race /metrics scrapes
        self.counts = collections.Counter()  # completed/shed_* totals
        self.lane_counts: Dict[str, collections.Counter] = {
            lane: collections.Counter() for lane in self.lanes}
        self.flight = FlightRecorder(capacity=flight_capacity)
        self._traced: list = []  # staged sampled requests (flat tuples)
        self._trace_dropped = 0
        _SCHEDULERS.add(self)

    # ------------------------------------------------------ request tracing
    def _tracing_on(self) -> bool:
        return tm.enabled() and trace_sample_rate() > 0.0

    @staticmethod
    def _phase_ms(t0_ns: int, t1_ns: int) -> Optional[float]:
        if not t0_ns or not t1_ns:
            return None
        return round(max(0, t1_ns - t0_ns) / 1e6, 3)

    def _flight_record(self, req: _Request, status: str, *,
                       cause: Optional[str] = None, end_ns: Optional[int] = None,
                       bucket: Optional[int] = None, traced: bool = False,
                       tokens_per_sec: Optional[float] = None,
                       draft_accept_rate: Optional[float] = None,
                       prefix_hit_rate: Optional[float] = None,
                       resumed_position: Optional[int] = None,
                       prefill_chunks: Optional[int] = None) -> dict:
        end_ns = end_ns or time.time_ns()
        rec = {
            "id": req.request_id,
            "lane": req.lane,
            "rows": req.rows,
            "bucket": bucket,
            "status": status,
            "cause": cause,
            "queue_ms": self._phase_ms(req.t_submit_ns,
                                       req.t_open_ns or end_ns),
            "fill_ms": self._phase_ms(req.t_open_ns, req.t_exec0_ns),
            "compute_ms": self._phase_ms(req.t_exec0_ns, req.t_exec1_ns),
            "total_ms": self._phase_ms(req.t_submit_ns, end_ns),
            "sampled": req.sampled,
            "traced": traced,
            "time": end_ns / 1e9,
        }
        if tokens_per_sec is not None:
            rec["tokens_per_sec"] = round(tokens_per_sec, 3)
        if draft_accept_rate is not None:
            # speculative decoding (serving/generate.py): the fraction of
            # draft proposals the target verified for THIS request
            rec["draft_accept_rate"] = round(draft_accept_rate, 4)
        if prefix_hit_rate is not None:
            # prefix cache (ISSUE 16): the batch's hit rate, this
            # request's resume point (0 = cold), and how many prompt
            # chunks the prefill ran as
            rec["prefix_hit_rate"] = round(prefix_hit_rate, 4)
        if resumed_position is not None:
            rec["resumed_position"] = int(resumed_position)
        if prefill_chunks is not None:
            rec["prefill_chunks"] = int(prefill_chunks)
        self.flight.record(rec)
        return rec

    def _stage_spans(self, req: _Request, outcome: str,
                     bucket: Optional[int] = None,
                     tokens_per_sec: Optional[float] = None,
                     end_ns: Optional[int] = None,
                     draft_accept_rate: Optional[float] = None,
                     prefix_hit_rate: Optional[float] = None,
                     resumed_position: Optional[int] = None,
                     prefill_chunks: Optional[int] = None):
        """Stage ONE sampled request for span export: a flat tuple append
        (no dicts, no registry lock — the hot-path finding behind
        :func:`collect_deferred_spans`). Thread identity is captured here
        so the spans land on the recording thread's trace row."""
        if len(self._traced) >= _TRACE_STAGE_MAX:
            self._trace_dropped += 1
            return
        th = threading.current_thread()
        self._traced.append(
            (req.request_id, req.lane, req.rows, req.t_submit_ns,
             req.t_open_ns, req.t_exec0_ns, req.t_exec1_ns, outcome,
             bucket, tokens_per_sec, end_ns or time.time_ns(),
             th.ident, th.name, draft_accept_rate,
             prefix_hit_rate, resumed_position, prefill_chunks))

    def _materialize_spans(self) -> List[dict]:
        """Staged tuples -> Chrome phase events (queue_wait / batch_fill /
        compute), cleared on read. Cold path: runs at telemetry export."""
        staged, self._traced = self._traced, []
        if self._trace_dropped:
            tm.counter("serving.trace_stage_dropped_total",
                       self._trace_dropped, model=self.model_id)
            self._trace_dropped = 0
        pid = os.getpid()
        out: List[dict] = []
        for (rid, lane, rows, t_submit, t_open, t_exec0, t_exec1, outcome,
             bucket, tps, end_ns, tid, tname, accept,
             hit_rate, resumed, chunks) in staged:
            base = {"request_id": rid, "model": self.model_id,
                    "lane": lane, "outcome": outcome}
            if not outcome.startswith("shed"):
                # completions/errors are recorded by the worker inside its
                # serving.batch span; sheds happen on the submit thread
                base["parent"] = "serving.batch"

            def ev(name, t0, t1, args):
                return {"name": name, "ph": "X", "pid": pid, "tid": tid,
                        "tname": tname, "ts": t0,
                        "dur": max(0, t1 - t0), "args": args}

            out.append(ev("serving.request.queue_wait", t_submit,
                          t_open or end_ns, base))
            if t_open and t_exec0:
                out.append(ev("serving.request.batch_fill", t_open,
                              t_exec0, base))
            if t_exec0 and t_exec1:
                args = dict(base, rows=rows)
                if bucket is not None:
                    args["bucket"] = bucket
                if tps is not None:
                    args["tokens_per_sec"] = round(tps, 3)
                if accept is not None:
                    # the per-request speculation ruler (ISSUE 15): how
                    # much of the draft's work the target verified
                    args["draft_accept_rate"] = round(accept, 4)
                if hit_rate is not None:
                    # prefix cache + chunked prefill (ISSUE 16): hit/miss
                    # and resume point per request, chunk count per batch
                    args["prefix_hit_rate"] = round(hit_rate, 4)
                if resumed is not None:
                    args["resumed_position"] = int(resumed)
                if chunks is not None:
                    args["prefill_chunks"] = int(chunks)
                out.append(ev("serving.request.compute", t_exec0,
                              t_exec1, args))
        return out

    # ------------------------------------------------------------ admission
    def submit(self, payload, *, lane: str = "interactive",
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None, **opts) -> Future:
        """Enqueue one request; returns a Future of the model result.
        Raises a :class:`ShedError` subclass instead of queueing when the
        scheduler is draining or the queue is full. ``request_id`` defaults
        to a fresh id; the HTTP layer passes the inbound ``X-Request-Id``."""
        if lane not in self._queues:
            raise ValueError(f"unknown lane {lane!r} (have {self.lanes})")
        rows = self.model.payload_rows(payload)
        now = time.monotonic()
        rate = trace_sample_rate() if tm.enabled() else 0.0
        req = _Request(
            payload=payload, rows=rows, future=Future(), lane=lane,
            opts_key=tuple(sorted(opts.items())), opts=opts, t_enqueue=now,
            deadline=None if deadline_ms is None else now + deadline_ms / 1e3,
            request_id=request_id or new_request_id(),
            sampled=rate > 0.0 and (rate >= 1.0 or random.random() < rate),
            t_submit_ns=time.time_ns())
        with self._cv:
            if self._worker_dead:
                # fail fast: the worker crashed past its restart budget (or
                # the scheduler was shut down) — enqueueing here would park
                # the future on a queue nothing will ever drain
                self._count_shed(req, "worker_dead")
                why = (f"worker crashed {self._restarts}x "
                       f"(budget {self.max_restarts})" if self._restarts
                       else "scheduler stopped")
                raise SchedulerStoppedError(f"{self.model_id}: {why} — "
                                            "no worker will run this request")
            if not self._accepting:
                self._count_shed(req, "draining")
                raise SchedulerDrainingError(
                    f"{self.model_id}: scheduler draining")
            if lane in self._brownout_lanes:
                # SLO budget exhausted (resilience.BrownoutController):
                # bulk lanes shed so the interactive promise survives
                self._count_shed(req, "brownout")
                raise BrownoutShedError(
                    f"{self.model_id}: lane {lane!r} browned out "
                    "(SLO error budget exhausted)")
            if self.breaker is not None:
                try:
                    self.breaker.allow()
                except CircuitOpenError:
                    self._count_shed(req, "circuit_open")
                    raise
            depth = sum(len(q) for q in self._queues.values())
            if depth >= self.queue_limit:
                self._count_shed(req, "queue_full")
                raise QueueFullError(
                    f"{self.model_id}: queue at capacity ({depth})")
            self._queues[lane].append(req)
            tm.gauge("serving.queue_depth", depth + 1, model=self.model_id)
            tm.gauge("serving.queue_depth", len(self._queues[lane]),
                     model=self.model_id, lane=lane)
            self._cv.notify()
        tm.counter("serving.requests_total", model=self.model_id, lane=lane)
        return req.future

    def _count_shed(self, req: _Request, reason: str):
        """Shared shed bookkeeping: counters (total + per-lane), the flight
        recorder, and — when tracing is on — the always-kept shed span."""
        self.counts[f"shed_{reason}"] += 1
        self.lane_counts[req.lane][f"shed_{reason}"] += 1
        tm.counter("serving.shed_total", model=self.model_id,
                   reason=reason, lane=req.lane)
        traced = self._tracing_on()
        self._flight_record(req, "shed", cause=reason, traced=traced)
        if traced:
            self._stage_spans(req, f"shed:{reason}")

    # --------------------------------------------------------------- worker
    def start(self) -> "BatchScheduler":
        with self._cv:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._supervised if self.supervised
                    else self._loop,
                    daemon=True, name=f"serving-{self.model_id}")
                self._thread.start()
        return self

    def set_brownout(self, lanes=()):
        """Shed ``lanes`` at submit time with :class:`BrownoutShedError`
        (the resilience.BrownoutController seam). Pass ``()`` to restore."""
        with self._cv:
            self._brownout_lanes = frozenset(lanes)
            self._cv.notify_all()

    def _supervised(self):
        """Watchdog wrapper around the worker loop: a crash fails the
        in-flight batch loudly (500 + flight-recorder cause), counts
        ``serving.worker_restarts_total``, and restarts the loop under the
        RetryPolicy backoff; ``max_restarts`` exhausted flips the model's
        ``serving.worker.<id>`` health check and fails everything still
        queued with :class:`SchedulerStoppedError` (docs/SERVING.md)."""
        while True:
            try:
                self._loop()
                return  # clean stop (drain/shutdown)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — the watchdog seam
                if not self._on_worker_crash(e):
                    return
                self.restart_policy.sleep_before_retry(self._restarts)

    def _on_worker_crash(self, exc: BaseException) -> bool:
        """Crash bookkeeping; returns True when the loop should restart."""
        cause = f"worker_crash: {exc!r}"[:200]
        with self._cv:
            batch, self._current_batch = self._current_batch, None
        # the in-flight batch's callers get a loud 500, never a hang
        for req in batch or ():
            if req.future.done():
                # a crash AFTER _run_batch resolved this rider (e.g. in the
                # post-result bookkeeping) — re-failing a FINISHED future
                # raises, which would kill the watchdog itself and leave
                # the queue dead with _worker_dead never set
                continue
            err_ns = time.time_ns()
            req.t_exec1_ns = req.t_exec1_ns or err_ns
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(WorkerCrashedError(
                    f"{self.model_id}: scheduler worker crashed executing "
                    f"this batch: {exc!r}"))
            self.counts["errors"] += 1
            self.lane_counts[req.lane]["errors"] += 1
            tm.counter("serving.request_errors_total",
                       model=self.model_id, lane=req.lane)
            self._flight_record(req, "error", cause=cause, end_ns=err_ns,
                                traced=self._tracing_on())
        if self.breaker is not None:
            self.breaker.record_error()
        self._restarts += 1
        self._batches_since_crash = 0
        tm.counter("serving.worker_restarts_total", model=self.model_id)
        record_anomaly("worker_crash",
                       f"{self.model_id}: {exc!r}"[:200],
                       source="serving", model=self.model_id)
        if self._restarts <= self.max_restarts:
            tm.set_health(f"serving.worker.{self.model_id}", True,
                          f"restarted after crash "
                          f"({self._restarts}/{self.max_restarts}): "
                          f"{exc!r}"[:200])
            return True
        # budget exhausted: the model is declared down — health flips, and
        # everything still queued fails loudly instead of hanging forever
        tm.set_health(f"serving.worker.{self.model_id}", False,
                      f"worker dead after {self._restarts} crashes "
                      f"(budget {self.max_restarts}): {exc!r}"[:200])
        with self._cv:
            self._worker_dead = True
            self._inflight = 0
            pending = [r for l in self.lanes for r in self._queues[l]]
            for l in self.lanes:
                self._queues[l].clear()
            self._cv.notify_all()
        for req in pending:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(SchedulerStoppedError(
                    f"{self.model_id}: worker crashed past its restart "
                    f"budget ({self.max_restarts}); request abandoned"))
            self._flight_record(req, "error", cause="worker_dead",
                                traced=self._tracing_on())
        return False

    def _shed(self, req: _Request, exc: ShedError, reason: str):
        self._count_shed(req, reason)
        if not req.future.set_running_or_notify_cancel():
            return
        req.future.set_exception(exc)

    def _sweep_expired_locked(self, now: float):
        for lane in self.lanes:
            q = self._queues[lane]
            kept = collections.deque()
            while q:
                req = q.popleft()
                if req.deadline is not None and now > req.deadline:
                    self._shed(req, DeadlineExceededError(
                        f"{self.model_id}: deadline expired after "
                        f"{(now - req.t_enqueue) * 1e3:.1f} ms in queue"),
                        "deadline")
                else:
                    kept.append(req)
            self._queues[lane] = q
            q.extend(kept)

    def _open_batch_locked(self) -> Optional[List[_Request]]:
        """Pop the head of the highest-priority non-empty lane."""
        for lane in self.lanes:
            if self._queues[lane]:
                req = self._queues[lane].popleft()
                req.t_open_ns = time.time_ns()  # queue wait ends here
                return [req]
        return None

    def _fill_batch_locked(self, batch: List[_Request]) -> int:
        """Admit compatible queued requests into the open batch (same lane
        first, then lower lanes — occupancy over strictness once the
        priority head is already in the batch). A request whose deadline
        expired while the batch was filling is shed here, not executed —
        the 429 contract holds even under a busy worker. Returns total
        rows."""
        head = batch[0]
        rows = sum(r.rows for r in batch)
        for lane in self.lanes:
            q = self._queues[lane]
            scan = len(q)
            for _ in range(scan):
                if rows >= self.max_batch:
                    return rows
                req = q[0]
                now = time.monotonic()
                if req.deadline is not None and now > req.deadline:
                    q.popleft()
                    self._shed(req, DeadlineExceededError(
                        f"{self.model_id}: deadline expired after "
                        f"{(now - req.t_enqueue) * 1e3:.1f} ms in queue"),
                        "deadline")
                    continue
                if req.opts_key != head.opts_key \
                        or rows + req.rows > self.max_batch:
                    break
                req.t_open_ns = time.time_ns()  # joins the open batch
                batch.append(q.popleft())
                rows += req.rows
        return rows

    def _loop(self):
        while True:
            with self._cv:
                while not self._stop \
                        and not any(self._queues[l] for l in self.lanes):
                    self._cv.wait(timeout=0.1)
                if self._stop \
                        and not any(self._queues[l] for l in self.lanes):
                    return
                self._sweep_expired_locked(time.monotonic())
                batch = self._open_batch_locked()
                if batch is None:
                    continue
                self._inflight = 1
                self._current_batch = batch  # the watchdog fails these
                                             # loudly if the loop dies
            # max-wait window: keep admitting until the batch is full or
            # max_wait_ms has passed since it opened (continuous batching).
            # The whole cycle (fill wait + execute) is one worker-thread
            # span, so the trace's serving-<model> row shows where the
            # worker's time goes between batches.
            t_open = time.monotonic()
            deadline = t_open + self.max_wait_ms / 1e3
            try:
                with tm.span("serving.worker.batch_cycle",
                             model=self.model_id) as cycle:
                    while True:
                        with self._cv:
                            rows = self._fill_batch_locked(batch)
                            if rows >= self.max_batch:
                                break
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            self._cv.wait(timeout=remaining)
                    if hasattr(cycle, "args"):  # not the disabled no-op
                        cycle.args["requests"] = len(batch)
                        cycle.args["rows"] = rows
                    self._run_batch(batch)
                    # every future resolved (result or handled error): the
                    # watchdog must not re-fail them if the loop dies later
                    self._current_batch = None
            finally:
                with self._cv:
                    self._inflight = 0
                    tm.gauge("serving.queue_depth",
                             sum(len(q) for q in self._queues.values()),
                             model=self.model_id)
                    # per-lane depths refresh on dequeue too — without this
                    # a drained lane's gauge stays at its submit-time high
                    # water forever (scrapes would show a phantom backlog)
                    for _lane, _q in self._queues.items():
                        tm.gauge("serving.queue_depth", len(_q),
                                 model=self.model_id, lane=_lane)
                    self._cv.notify_all()

    def _drain_priority_once(self):
        """Chunked-prefill yield hook (serving/generate.py): between an
        outer batch's prompt chunks, run up to two queued PRIORITY-lane
        batches so a long-prompt bulk burst cannot spike interactive
        decode p99 — the whole point of chunking. Only wired into
        non-priority batches (``_run_batch``), so the nesting depth is
        exactly one: an interactive batch never yields. The outer batch
        stays parked on ``_current_batch`` around each inner run so the
        watchdog's loud-failure contract keeps covering it."""
        ran = 0
        while ran < 2:
            with self._cv:
                self._sweep_expired_locked(time.monotonic())
                if not self._queues[self.lanes[0]]:
                    break
                inner = self._open_batch_locked()
                if inner is None:
                    break
                self._fill_batch_locked(inner)
                outer = self._current_batch
                self._current_batch = inner
            try:
                self._run_batch(inner)
            finally:
                with self._cv:
                    self._current_batch = outer
            ran += 1
        if ran:
            tm.counter("serving.prefill_yield_preemptions_total", ran,
                       model=self.model_id)

    def _run_batch(self, batch: List[_Request]):
        t0 = time.monotonic()
        self._batch_seq += 1
        seq = self._batch_seq  # the serving_* faults' @step concept
        # the injected worker crash escapes to the watchdog (_supervised):
        # the REAL mechanism a broken scheduler exhibits — an exception in
        # the loop machinery itself, outside the per-batch model-error catch
        if fl.get_injector().fire(fl.SERVING_WORKER_CRASH,
                                  step=seq) is not None:
            raise RuntimeError(
                f"{self.model_id}: injected serving worker crash "
                f"(batch {seq})")
        tracing = self._tracing_on()
        # batch-level pad/device sub-spans ride the head-sampling decision:
        # a batch with ANY sampled request gets the detailed execute spans
        trace_batch = tracing and any(r.sampled for r in batch)
        exec0_ns = time.time_ns()
        for req in batch:
            req.t_exec0_ns = exec0_ns
        # chunked prefill interleave: a NON-priority batch on a chunking
        # model hands the device back between prompt chunks; an
        # interactive batch never yields (depth stays 1, no starvation of
        # the batch itself — at most 2 inner batches per chunk boundary)
        extra = {}
        if (batch[0].lane != self.lanes[0]
                and getattr(self.model, "supports_chunked_prefill", False)):
            extra["_yield"] = self._drain_priority_once
        with tm.span("serving.batch", model=self.model_id,
                     requests=len(batch), lane=batch[0].lane):
            try:
                results, stats = self.model.execute(
                    [r.payload for r in batch], _trace=trace_batch,
                    _step=seq, **extra, **batch[0].opts)
            except ShedError as e:
                # an EXECUTE-time shed (paged-pool exhaustion): a
                # first-class 429 with its own cause, NOT a server error —
                # the riders' futures carry the ShedError (the HTTP layer
                # answers 429 + Retry-After), the per-lane shed counters
                # and flight-recorder cause record it, and the breaker
                # never hears about it (the model is healthy; the pool is
                # full — r13 shed contract, new cause)
                err_ns = time.time_ns()
                reason = getattr(e, "shed_reason", "shed")
                for req in batch:
                    req.t_exec1_ns = err_ns
                    self._shed(req, e, reason)
                return
            except Exception as e:  # a bad request fails its batch, never
                err_ns = time.time_ns()  # the worker (ParallelInference
                for req in batch:        # contract)
                    req.t_exec1_ns = err_ns
                    if req.future.set_running_or_notify_cancel():
                        req.future.set_exception(e)
                    self.counts["errors"] += 1
                    self.lane_counts[req.lane]["errors"] += 1
                    tm.counter("serving.request_errors_total",
                               model=self.model_id, lane=req.lane)
                    # errors are always kept (tracing permitting)
                    self._flight_record(req, "error", cause=repr(e)[:200],
                                        end_ns=err_ns, traced=tracing)
                    if tracing:
                        self._stage_spans(req, "error", end_ns=err_ns)
                tm.counter("serving.batch_errors_total", model=self.model_id)
                if self.breaker is not None and not isinstance(
                        e, (KeyError, TypeError, ValueError)):
                    # one failed batch = one breaker outcome: enough of
                    # these in a row fast-fails instead of queueing more
                    # doomed work (resilience.CircuitBreaker). The
                    # client-shaped family (the server's HTTP 400 mapping)
                    # is excluded — a buggy client's malformed payloads
                    # must not open the breaker and 503 a healthy model
                    # for everyone else
                    self.breaker.record_error()
                return
            if self.breaker is not None:
                self.breaker.record_success()
            self._batches_since_crash += 1
            if self._restarts and \
                    self._batches_since_crash >= self.restart_reset_batches:
                # a sustained healthy run pays the crash budget back: the
                # watchdog bounds crash LOOPS, not lifetime crashes
                self._restarts = 0
            exec1_ns = time.time_ns()
            now = time.monotonic()
            padded = stats.get("padded_rows")
            decode_s = stats.get("decode_seconds")
            decode_toks = stats.get("decode_tokens")
            accept_rates = stats.get("draft_accept_rate")  # per rider, or None
            hit_rate = stats.get("prefix_hit_rate")        # batch-level
            resumed = stats.get("resumed_positions")       # per rider
            chunks = stats.get("prefill_chunks")
            lane_done: collections.Counter = collections.Counter()
            for ridx, (req, res) in enumerate(zip(batch, results)):
                req.t_exec1_ns = exec1_ns
                if req.future.set_running_or_notify_cancel():
                    req.future.set_result(res)
                lat = now - req.t_enqueue
                self.latencies.add(lat)
                self.lane_latencies[req.lane].add(lat)
                with self._ts_lock:
                    self._completed_ts.append(now)
                self.counts["completed"] += 1
                self.lane_counts[req.lane]["completed"] += 1
                lane_done[req.lane] += 1
                tm.observe("serving.request_latency_seconds", lat,
                           model=self.model_id, lane=req.lane)
                tps = None
                if decode_s and decode_toks:
                    # per-request decode throughput: this request's tokens
                    # over the batch's decode wall (incl. prefill)
                    try:
                        tps = len(res) / decode_s
                    except TypeError:
                        tps = None
                    if tps is not None:
                        tm.observe("serving.decode_tokens_per_sec", tps,
                                   model=self.model_id, lane=req.lane)
                rate = (accept_rates[ridx]
                        if accept_rates and ridx < len(accept_rates)
                        else None)
                rpos = (resumed[ridx]
                        if resumed and ridx < len(resumed) else None)
                keep = tracing and (req.sampled
                                    or lat * 1e3 > SLOW_REQUEST_MS)
                self._flight_record(req, "ok", end_ns=exec1_ns,
                                    bucket=padded, traced=keep,
                                    tokens_per_sec=tps,
                                    draft_accept_rate=rate,
                                    prefix_hit_rate=hit_rate,
                                    resumed_position=rpos,
                                    prefill_chunks=chunks)
                if keep:
                    self._stage_spans(
                        req, "ok" if req.sampled else "slow",
                        bucket=padded, tokens_per_sec=tps, end_ns=exec1_ns,
                        draft_accept_rate=rate, prefix_hit_rate=hit_rate,
                        resumed_position=rpos, prefill_chunks=chunks)
        # one counter bump per lane per batch, not per request — registry
        # lock acquisitions on the worker are GIL time stolen from other
        # models' workers (the mixed-bench finding; see _LatencyWindow.add)
        for lane, done in lane_done.items():
            tm.counter("serving.completed_total", done,
                       model=self.model_id, lane=lane)
        tm.counter("serving.batches_total", model=self.model_id)
        tm.counter("serving.recompiles_total", stats.get("recompiles", 0),
                   model=self.model_id)
        if stats.get("spec_accept_rate") is not None:
            # batch-mean draft acceptance — the /metrics companion of the
            # per-request flight-recorder field (ISSUE 15 satellite)
            tm.gauge("serving.spec_accept_rate",
                     float(stats["spec_accept_rate"]), model=self.model_id)
        if padded:
            tm.observe("serving.batch_occupancy",
                       stats["real_rows"] / padded,
                       model=self.model_id, lane=batch[0].lane)
        tm.observe("serving.batch_exec_seconds", now - t0,
                   model=self.model_id)
        gauges = ((0.5, "serving.latency_p50_seconds"),
                  (0.99, "serving.latency_p99_seconds"))
        # one sort per touched window, and only the lanes THIS batch fed —
        # idle lanes keep their last gauge (collect_metrics refreshes all
        # lanes at scrape time anyway)
        windows = [(self.latencies, {})] + [
            (self.lane_latencies[lane], {"lane": lane})
            for lane in {r.lane for r in batch}]
        for win, extra in windows:
            for (q, g), val in zip(gauges,
                                   win.quantiles([q for q, _g in gauges])):
                if val is not None:
                    tm.gauge(g, val, model=self.model_id, **extra)

    # ----------------------------------------------------------- lifecycle
    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain (the r11 SIGTERM seam, serving-side): stop
        accepting, FINISH everything already queued, then stop the worker.
        Returns True when the queues emptied within ``timeout``."""
        with self._cv:
            self._accepting = False
            self._cv.notify_all()
        deadline = time.monotonic() + timeout
        with self._cv:
            while (any(self._queues[l] for l in self.lanes)
                   or self._inflight) and time.monotonic() < deadline:
                self._cv.wait(timeout=0.1)
            drained = not any(self._queues[l] for l in self.lanes) \
                and not self._inflight
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return drained

    def shutdown(self):
        """Immediate stop: fail everything still queued loudly (a pending
        future must never outlive the worker that would have run it), and
        make any LATER submit fail fast (SchedulerStoppedError) instead of
        enqueueing into the dead queue."""
        with self._cv:
            self._accepting = False
            self._stop = True
            self._worker_dead = True
            pending = [r for l in self.lanes for r in self._queues[l]]
            for l in self.lanes:
                self._queues[l].clear()
            self._cv.notify_all()
        for req in pending:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(
                    SchedulerDrainingError(f"{self.model_id}: shut down"))
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ---------------------------------------------------------------- stats
    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def lane_queue_depths(self) -> Dict[str, int]:
        with self._cv:
            return {lane: len(q) for lane, q in self._queues.items()}

    def qps(self, window_s: float = 10.0) -> float:
        now = time.monotonic()
        with self._ts_lock:
            n = sum(1 for t in self._completed_ts if now - t <= window_s)
        return n / window_s

    def stats(self) -> dict:
        p50 = self.latencies.quantile(0.5)
        p99 = self.latencies.quantile(0.99)

        def _ms(v):
            return None if v is None else round(v * 1e3, 3)

        lanes = {}
        for lane in self.lanes:
            lc = self.lane_counts[lane]
            win = self.lane_latencies[lane]
            lanes[lane] = {
                "completed": lc["completed"],
                "errors": lc["errors"],
                # per-lane shed counts BY CAUSE (deadline vs queue_full vs
                # draining) — the ISSUE 12 attribution satellite
                "shed": {k[len("shed_"):]: v for k, v in lc.items()
                         if k.startswith("shed_")},
                "latency_p50_ms": _ms(win.quantile(0.5)),
                "latency_p99_ms": _ms(win.quantile(0.99)),
            }
        return {
            "queue_depth": self.queue_depth(),
            "accepting": self._accepting,
            "worker_alive": (self._thread is not None
                             and self._thread.is_alive()
                             and not self._worker_dead),
            "worker_restarts": self._restarts,
            "breaker": (self.breaker.status()
                        if self.breaker is not None else None),
            "brownout_lanes": sorted(self._brownout_lanes),
            "completed": self.counts["completed"],
            "errors": self.counts["errors"],
            "shed": {k[len("shed_"):]: v for k, v in self.counts.items()
                     if k.startswith("shed_")},
            "lanes": lanes,
            "qps_10s": round(self.qps(), 3),
            "latency_p50_ms": _ms(p50),
            "latency_p99_ms": _ms(p99),
            "flight_recorder_depth": len(self.flight),
            "max_batch": self.max_batch,
            "max_wait_ms": self.max_wait_ms,
            "queue_limit": self.queue_limit,
        }
