"""Production serving tier (docs/SERVING.md).

The "millions of users" half of the north star: continuous/dynamic
batching with deadline-aware priority queues (``scheduler``), multi-model
multi-tenant routing with per-model admission control (``router``),
paged-KV-cache autoregressive decode with shared-prefix KV reuse
(refcounted blocks + radix prefix cache + copy-on-write), chunked
prefill, speculative decoding and weight-only int8 for the transformer
stack (``generate``/``paged``/``quantize``), and an HTTP model server
with queue-depth-driven load
shedding and SIGTERM graceful drain (``server``) — all riding the r8
compile-once substrate (bucketing + AOT warmup), so steady-state serving
performs ZERO XLA compiles. Above it all sits the fleet (``fleet``/
``fleet_worker``): a front-tier router spreading traffic over N worker
``ModelServer`` processes with prefix-affinity rendezvous routing,
health-aware ring membership, failover, supervision, and fleet-wide
rolling reload (docs/SERVING.md#fleet).

    from deeplearning4j_tpu.serving import (ModelRouter, ModelServer,
                                            ServingModel)

    router = ModelRouter()
    router.register(ServingModel(net, "lenet"))           # live model
    router.load("bert", "/models/bert.zip", kind="generate")
    server = ModelServer(router, port=8080).start()        # warms buckets
"""

from deeplearning4j_tpu.serving.fleet import (FleetRouter, FleetWorker,
                                              affinity_key, fleet_spec,
                                              rendezvous_pick)
from deeplearning4j_tpu.serving.generate import Generator
from deeplearning4j_tpu.serving.model import ServingModel
from deeplearning4j_tpu.serving.paged import (BlockPool, PoolExhaustedError,
                                              PrefixCache)
from deeplearning4j_tpu.serving.quantize import (INT8_LOGIT_TOL,
                                                 QuantizedParams)
from deeplearning4j_tpu.serving.resilience import (BrownoutController,
                                                   BrownoutShedError,
                                                   CircuitBreaker,
                                                   CircuitOpenError,
                                                   FleetUnavailableError,
                                                   ModelLoadError,
                                                   ReloadRejectedError,
                                                   SchedulerStoppedError,
                                                   WorkerCrashedError,
                                                   WorkerProxyError)
from deeplearning4j_tpu.serving.router import (ModelRouter,
                                               UnknownModelError,
                                               current_status)
from deeplearning4j_tpu.serving.scheduler import (BatchScheduler,
                                                  DeadlineExceededError,
                                                  FlightRecorder,
                                                  QueueFullError,
                                                  SchedulerDrainingError,
                                                  ShedError,
                                                  new_request_id,
                                                  trace_sample_rate)
from deeplearning4j_tpu.serving.server import ModelServer

__all__ = [
    "BatchScheduler",
    "BlockPool",
    "BrownoutController",
    "BrownoutShedError",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceededError",
    "FleetRouter",
    "FleetUnavailableError",
    "FleetWorker",
    "FlightRecorder",
    "Generator",
    "INT8_LOGIT_TOL",
    "ModelLoadError",
    "ModelRouter",
    "ModelServer",
    "PoolExhaustedError",
    "PrefixCache",
    "QuantizedParams",
    "QueueFullError",
    "ReloadRejectedError",
    "SchedulerDrainingError",
    "SchedulerStoppedError",
    "ServingModel",
    "ShedError",
    "UnknownModelError",
    "WorkerCrashedError",
    "WorkerProxyError",
    "affinity_key",
    "current_status",
    "fleet_spec",
    "new_request_id",
    "rendezvous_pick",
    "trace_sample_rate",
]
