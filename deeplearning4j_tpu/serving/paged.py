"""Paged/block KV cache for the decode tier (docs/SERVING.md).

The contiguous r13 cache allocated ``(B, H, max_length, Dh)`` per layer for
every decode batch — every stream paid ``max_length`` positions of device
memory no matter how short its context, and the ceiling on concurrent
streams per device was ``pool_bytes / (max_length * per_token_bytes)``.
This module replaces that with the vLLM-style paged layout:

- **One slot-flat pool per layer** — ``(S, H, Dh)`` with
  ``S = (num_blocks + 1) * block_size`` token slots. Block 0 is the
  RESERVED TRASH BLOCK: every position outside a stream's reservation
  (bucket padding, padded batch rows) scatters there and every read is
  position-masked before the softmax, so trash content is never visible.
- **A page table per stream** — the host-side list of physical block ids
  backing logical positions ``[0, ceil((len + max_new) / block_size) *
  block_size)``. The decode executable takes the table as data
  ``(B, max_blocks)`` and expands it to per-position slot indices in-jit,
  so ONE executable (per batch bucket) serves every mix of context
  lengths with zero recompiles — context length is a value, not a shape.
- **All-or-nothing admission** — :meth:`BlockPool.reserve` either hands a
  batch every block its streams need for the WHOLE generation (prompt +
  ``max_new_tokens``, so a stream can never run out mid-decode) or raises
  :class:`PoolExhaustedError` with nothing allocated — the scheduler
  sheds the batch 429 + Retry-After (the r13 shed contract, new cause
  ``pool_exhausted``) instead of OOMing. Blocks free on completion/eos
  (the decode loop exits early once every live row has emitted eos) and
  on shed.
- **Refcounted blocks + shared prefixes** — every allocated block carries
  a refcount: +1 per stream whose page table maps it, +1 when the
  :class:`PrefixCache` trie indexes it. ``release``/eos early-exit/shed
  DECREMENT instead of freeing outright, so a block shared by N streams
  (one physical copy of a common system-prompt prefix) returns to the
  free list only when the last reference drops — the leak/double-free
  seam :meth:`conservation` audits and the health probe asserts.
- **Copy-on-write** — a stream about to WRITE into a block someone else
  also references first gets a private copy (:meth:`cow_split` remaps
  the refcounts; the generator's ``_copy_block`` program copies the
  device rows). Shared prompt-prefix blocks are never written after
  their first fill, so COW fires only at the write/share boundary (a
  block-aligned full-prefix hit whose last token must be recomputed for
  logits), but the mechanism is what makes sharing SAFE by construction.

Rollback semantics (speculative decoding, serving/generate.py): rejected
window positions keep their reservation — rolling back is pure position
bookkeeping on the host — and their stale K/V rows are PROVABLY
overwritten before any read: the next window write covers ``[pos + m,
pos + m + w)`` ⊇ the rejected ``[pos + m, pos + w)`` (``m ≥ 1``), and
every attention read in between is masked to ``k_pos <= position``.
Rollback never touches shared prefix blocks: generation writes land at
positions ``>= prompt_len``, past every cacheable (full-prompt) block.

Gauges: ``serving.kv_pool_blocks_total`` / ``_free``,
``serving.concurrent_streams``, ``serving.prefix_blocks_shared``
(+ per-pool high-water in :meth:`stats`), the inputs to the
``concurrent_streams_per_device`` bench metric.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.serving.resilience import PoolExhaustedError
from deeplearning4j_tpu.util import telemetry as tm

__all__ = ["BlockPool", "PrefixCache", "PoolExhaustedError"]


class BlockPool:
    """Host-side allocator + device-side slot-flat K/V pools (module doc).

    ``num_blocks`` usable blocks of ``block_size`` token slots each; the
    device tensors carry one extra (trash) block at index 0. Device state
    lives in ``self.pools`` — one ``{"k": (S,H,Dh), "v": (S,H,Dh)}`` per
    transformer layer, created by the blocks' ``init_pool`` and donated
    through the decode executables (the generator threads the returned
    pools back). Allocation is REFCOUNTED: ``reserve`` hands out blocks
    at refcount 1, ``incref`` adds holders (prefix-cache hits, the trie
    index itself), and a block frees only when ``decref`` reaches 0."""

    def __init__(self, blocks, *, block_size: int, num_blocks: int,
                 max_length: int, model_id: str = "",
                 dtype=None):
        import jax.numpy as jnp

        if block_size < 1 or num_blocks < 1:
            raise ValueError("block_size and num_blocks must be >= 1")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_length = int(max_length)
        self.model_id = str(model_id)
        #: page-table width: enough blocks to map every logical position
        self.max_blocks_per_stream = math.ceil(self.max_length
                                               / self.block_size)
        self.num_slots = (self.num_blocks + 1) * self.block_size
        self.pools = [blk.init_pool(self.num_slots,
                                    dtype or jnp.float32)
                      for blk in blocks]
        self._lock = threading.RLock()
        # block 0 is the trash block — never handed out
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        #: refcount per ALLOCATED block (absent = free)
        self._ref: Dict[int, int] = {}
        self._streams = 0
        self.peak_streams = 0
        self._gauges()

    # ---------------------------------------------------------- accounting
    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Blocks one stream needs for its WHOLE generation."""
        return math.ceil((prompt_len + max_new) / self.block_size)

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(int(block), 0)

    def shared_blocks(self) -> int:
        """Blocks with more than one holder — the physical dedup the
        prefix cache achieves (``serving.prefix_blocks_shared``)."""
        with self._lock:
            return sum(1 for r in self._ref.values() if r > 1)

    def bytes_per_token(self) -> int:
        """Device bytes one token slot costs across every layer (K + V).
        Pure shape arithmetic — this runs on every stats poll, and
        slicing (``p["k"][0]``) would dispatch an eager device gather per
        layer just to read sizes."""
        return sum(int(p["k"].nbytes // p["k"].shape[0]
                       + p["v"].nbytes // p["v"].shape[0])
                   for p in self.pools)

    def pool_bytes(self) -> int:
        """Total device bytes of the usable pool (trash block excluded)."""
        return self.num_blocks * self.block_size * self.bytes_per_token()

    def contiguous_stream_ceiling(self) -> int:
        """How many streams the SAME bytes hold under the r13 contiguous
        layout (every stream pays ``max_length`` slots) — the baseline the
        ``concurrent_streams_per_device`` gate must beat."""
        return (self.num_blocks * self.block_size) // self.max_length

    def _gauges(self):
        tm.gauge("serving.kv_pool_blocks_total", self.num_blocks,
                 model=self.model_id)
        tm.gauge("serving.kv_pool_blocks_free", len(self._free),
                 model=self.model_id)
        tm.gauge("serving.concurrent_streams", self._streams,
                 model=self.model_id)
        tm.gauge("serving.prefix_blocks_shared",
                 sum(1 for r in self._ref.values() if r > 1),
                 model=self.model_id)

    # ----------------------------------------------------------- admission
    def reserve(self, counts: Sequence[int]) -> List[List[int]]:
        """All-or-nothing: allocate ``counts[i]`` blocks for stream i
        (each at refcount 1), or raise :class:`PoolExhaustedError` having
        allocated NOTHING."""
        need = int(sum(counts))
        with self._lock:
            if need > len(self._free):
                tm.counter("serving.pool_exhausted_total",
                           model=self.model_id)
                raise PoolExhaustedError(
                    f"{self.model_id or 'paged-kv'}: batch needs {need} "
                    f"KV blocks, pool has {len(self._free)} free "
                    f"(of {self.num_blocks})")
            out = []
            for c in counts:
                blocks = [self._free.pop() for _ in range(int(c))]
                for b in blocks:
                    self._ref[b] = 1
                out.append(blocks)
            self._streams += len(counts)
            self.peak_streams = max(self.peak_streams, self._streams)
            self._gauges()
            return out

    def incref(self, blocks: Sequence[int]):
        """Add one holder to each block (a prefix-cache hit sharing the
        physical block, or the trie indexing it)."""
        with self._lock:
            for b in blocks:
                b = int(b)
                if b not in self._ref:
                    raise ValueError(
                        f"incref of unallocated block {b} "
                        f"({self.model_id or 'paged-kv'})")
                self._ref[b] += 1
            self._gauges()

    def decref(self, blocks: Sequence[int]):
        """Drop one holder from each block; a block frees only at
        refcount 0. Decref of a free block is a DOUBLE-FREE and raises —
        the bug class :meth:`conservation` exists to catch."""
        with self._lock:
            for b in blocks:
                b = int(b)
                r = self._ref.get(b)
                if r is None:
                    raise ValueError(
                        f"double-free: decref of free block {b} "
                        f"({self.model_id or 'paged-kv'})")
                if r <= 1:
                    del self._ref[b]
                    self._free.append(b)
                else:
                    self._ref[b] = r - 1
            self._gauges()

    def release(self, tables: Sequence[Sequence[int]]):
        """Drop every stream's hold on its blocks (eos / batch done /
        shed rollback). Shared blocks — a prefix another stream or the
        trie still references — stay allocated; only the LAST holder
        returns a block to the free list (the ISSUE 16 refcount fix: the
        eos early-exit used to free outright)."""
        with self._lock:
            for t in tables:
                self.decref(t)
            self._streams = max(0, self._streams - len(list(tables)))
            self._gauges()

    def cow_split(self, block: int) -> int:
        """Copy-on-write split: give the caller a PRIVATE block in place
        of shared ``block`` — allocates a fresh block at refcount 1 and
        drops the caller's hold on the original (which the other holders
        keep). The caller must copy the device rows (the generator's
        ``_copy_block`` program) before writing. Raises
        :class:`PoolExhaustedError` (nothing changed) when no block is
        free."""
        with self._lock:
            b = int(block)
            if b not in self._ref:
                raise ValueError(f"cow_split of free block {b}")
            if not self._free:
                tm.counter("serving.pool_exhausted_total",
                           model=self.model_id)
                raise PoolExhaustedError(
                    f"{self.model_id or 'paged-kv'}: COW split needs 1 "
                    f"free block, pool has 0 (of {self.num_blocks})")
            nb = self._free.pop()
            self._ref[nb] = 1
            self.decref([b])
            tm.counter("serving.prefix_cow_splits_total",
                       model=self.model_id)
            self._gauges()
            return nb

    # -------------------------------------------------------- conservation
    def conservation(self) -> Tuple[bool, str]:
        """Audit the allocator invariants (the all-trash health probe's
        steady-state leak/double-free check, docs/SERVING.md):
        free + allocated == num_blocks, no block both free and allocated,
        no duplicate free-list entries, every refcount >= 1, and the
        trash block never tracked. Returns (ok, detail)."""
        with self._lock:
            free = list(self._free)
            refs = dict(self._ref)
        problems = []
        if len(set(free)) != len(free):
            problems.append("duplicate free-list entries (double-free)")
        if 0 in free or 0 in refs:
            problems.append("trash block 0 entered the allocator")
        overlap = set(free) & set(refs)
        if overlap:
            problems.append(f"{len(overlap)} block(s) both free and "
                            f"allocated ({sorted(overlap)[:4]}…)")
        bad_ref = [b for b, r in refs.items() if r < 1]
        if bad_ref:
            problems.append(f"refcount < 1 on {bad_ref[:4]}")
        total = len(set(free)) + len(refs)
        if total != self.num_blocks:
            kind = "leak" if total < self.num_blocks else "over-count"
            problems.append(
                f"{kind}: free {len(set(free))} + allocated {len(refs)} "
                f"= {total} != {self.num_blocks} blocks")
        return (not problems,
                "; ".join(problems) if problems else
                f"free {len(free)} + allocated {len(refs)} "
                f"== {self.num_blocks}")

    # ------------------------------------------------------------ programs
    def table_array(self, tables: Sequence[Sequence[int]],
                    batch: int) -> np.ndarray:
        """Page tables as the decode executable's (B, max_blocks) int32
        input — unallocated entries (and padded batch rows) point at the
        trash block (0)."""
        out = np.zeros((batch, self.max_blocks_per_stream), np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = np.asarray(t, np.int32)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "block_size": self.block_size,
                "blocks_total": self.num_blocks,
                "blocks_free": len(self._free),
                "blocks_shared": sum(1 for r in self._ref.values()
                                     if r > 1),
                "streams": self._streams,
                "peak_streams": self.peak_streams,
                "pool_bytes": self.pool_bytes(),
                "contiguous_stream_ceiling":
                    self.contiguous_stream_ceiling(),
                # in-use fraction: the fleet front tier folds this into
                # /v1/fleet so an operator sees which worker's pool a hot
                # prefix is concentrating on (docs/SERVING.md#fleet)
                "utilization": round(
                    1.0 - len(self._free) / max(1, self.num_blocks), 4),
            }


class _TrieNode:
    """One block-granular radix-trie edge: ``key`` (a block_size-token
    tuple) → the physical block caching those tokens' K/V."""

    __slots__ = ("key", "block", "parent", "children", "pending",
                 "last_used")

    def __init__(self, key, block: int, parent):
        self.key = key
        self.block = int(block)
        self.parent = parent
        self.children: Dict[tuple, "_TrieNode"] = {}
        #: inserted this batch — device content not yet written by the
        #: owning stream's prefill, so sharers may take the BLOCKS (the
        #: owner's rows fill them inside the same program) but must still
        #: COMPUTE those positions themselves
        self.pending = True
        self.last_used = 0


class PrefixCache:
    """Radix/trie index over token prefixes → chains of cached KV blocks
    (the ISSUE 16 shared-prefix tentpole, docs/SERVING.md#prefix-cache).

    The trie is BLOCK-GRANULAR: each edge is a full ``block_size``-token
    tuple, so only prompt prefixes that fill whole blocks are indexed —
    the partial tail block (which generation writes into) stays private
    to its stream by construction, and shared blocks are therefore never
    written after their first fill. Every indexed block carries one
    trie hold on the :class:`BlockPool` refcount in addition to its
    stream holds; eviction (LRU leaves whose only holder is the trie)
    runs when admission would otherwise shed or grow.

    ``match`` walks the trie for a prompt, increfs the matched chain
    (the caller's stream holds) and reports how many leading tokens are
    COMMITTED (written by a prior batch) — the resume point prefill may
    skip. Nodes inserted for the current batch are ``pending`` until
    :meth:`commit`: a same-batch sharer takes their blocks (byte dedup)
    but recomputes their positions (the write-before-read ordering only
    holds inside one program for identical window shapes). ``flush``
    drops everything — pool growth and the exception-path pool reset
    destroy cached device content, so the trie must forget it."""

    def __init__(self, pool: BlockPool):
        self.pool = pool
        self.block_size = pool.block_size
        self.root: Dict[tuple, _TrieNode] = {}
        self._nodes = 0
        self._tick = 0
        # lifetime telemetry (serving.prefix_cache_hit_rate)
        self.lookups = 0
        self.hits = 0
        self.lookup_tokens = 0
        self.hit_tokens = 0
        self.evictions = 0

    # -------------------------------------------------------------- match
    def _keys(self, tokens: Sequence[int]):
        bs = self.block_size
        n = len(tokens) // bs
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(n)]

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Walk the trie along ``tokens``' full blocks. Returns
        ``(blocks, committed_tokens)``: the matched chain's block ids —
        each increffed as the calling stream's hold — and how many
        leading tokens are committed (a prior batch's prefill wrote
        them; the caller may resume from there). Pending blocks extend
        ``blocks`` (physical sharing) but not ``committed_tokens``."""
        with self.pool._lock:
            self._tick += 1
            self.lookups += 1
            self.lookup_tokens += len(tokens)
            blocks: List[int] = []
            committed = 0
            level = self.root
            for key in self._keys(tokens):
                node = level.get(key)
                if node is None:
                    break
                node.last_used = self._tick
                blocks.append(node.block)
                if not node.pending and committed == len(blocks) - 1:
                    committed += 1
                level = node.children
            if blocks:
                self.hits += 1
                self.hit_tokens += committed * self.block_size
                self.pool.incref(blocks)
            return blocks, committed * self.block_size

    # -------------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int],
               table: Sequence[int]) -> List[_TrieNode]:
        """Index ``tokens``' full prompt blocks as ``table``'s leading
        blocks. Existing nodes are kept (their block is already shared
        into ``table`` by ``match``); each NEW node takes one trie hold
        (incref) on its block and stays ``pending`` until the caller
        :meth:`commit`\\ s it. Returns the new nodes — pending
        bookkeeping is PER BATCH (the caller holds the list), because a
        chunk-yield can nest another batch's admit/commit inside this
        batch's prefill."""
        with self.pool._lock:
            self._tick += 1
            added: List[_TrieNode] = []
            level = self.root
            parent = None
            for i, key in enumerate(self._keys(tokens)):
                node = level.get(key)
                if node is None:
                    node = _TrieNode(key, table[i], parent)
                    self.pool.incref([node.block])
                    level[key] = node
                    self._nodes += 1
                    added.append(node)
                node.last_used = self._tick
                parent = node
                level = node.children
            return added

    def commit(self, nodes: Sequence[_TrieNode]):
        """Mark a batch's inserted nodes committed — their device content
        is now written, so FUTURE batches may resume past them (called by
        the generator right after its prefill executes)."""
        with self.pool._lock:
            for node in nodes:
                node.pending = False

    def rollback(self, nodes: Sequence[_TrieNode]):
        """Un-insert a batch's pending nodes (admission failed before any
        device write — their blocks hold no valid content and must not
        linger in the trie). Reverse insertion order, so children drop
        before parents. A node that acquired children is SKIPPED: a
        chunk-yield-nested batch built (and possibly committed) a subtree
        under it, and dropping it would orphan that subtree — the node
        stays pending (never matched as committed) until :meth:`evict`
        reclaims it as an abandoned leaf or :meth:`flush` tears down."""
        with self.pool._lock:
            for node in reversed(list(nodes)):
                if node.pending and not node.children:
                    self._drop_node(node)

    # ------------------------------------------------------------ eviction
    def _leaves(self):
        out = []

        def walk(level):
            for node in level.values():
                if node.children:
                    walk(node.children)
                else:
                    out.append(node)

        walk(self.root)
        return out

    def _drop_node(self, node: _TrieNode):
        level = node.parent.children if node.parent is not None else self.root
        level.pop(node.key, None)
        self._nodes -= 1
        self.pool.decref([node.block])

    def evict(self, want_free: int) -> int:
        """LRU-evict cache-only leaves (no children, no stream holds —
        pool refcount exactly the trie's 1) until ``want_free`` blocks
        are free or nothing more is evictable. Returns blocks freed.
        Walks leaf-up: freeing a leaf may expose its parent. Pending
        leaves at refcount 1 are evictable too: a live batch always holds
        a stream ref on its pending blocks (ref >= 2), so pending+1 can
        only be a rollback-skipped abandoned node (see :meth:`rollback`)
        that nothing will ever commit."""
        freed = 0
        with self.pool._lock:
            while len(self.pool._free) < want_free:
                victims = [n for n in self._leaves()
                           if self.pool._ref.get(n.block, 0) == 1]
                if not victims:
                    break
                node = min(victims, key=lambda n: n.last_used)
                self._drop_node(node)
                self.evictions += 1
                freed += 1
            if freed:
                tm.counter("serving.prefix_cache_evictions_total", freed,
                           model=self.pool.model_id)
        return freed

    def flush(self):
        """Forget every cached prefix and drop the trie's holds. Called
        on pool growth and the exception-path pool reset — both replace
        the device buffers, so every cached K/V row is gone."""
        with self.pool._lock:
            # leaf-up teardown: dropping a leaf exposes its parent
            while self.root:
                for node in self._leaves():
                    self._drop_node(node)

    def rebind(self, pool: BlockPool):
        """Point the (flushed) cache at a replacement pool — used by the
        generator after auto-growth swaps in a bigger :class:`BlockPool`
        (lifetime hit/miss telemetry carries over)."""
        if self.root:
            raise RuntimeError("rebind of a non-empty PrefixCache — "
                               "flush() first")
        self.pool = pool
        self.block_size = pool.block_size

    # --------------------------------------------------------------- audit
    def check(self, strict_idle: bool = False) -> Tuple[bool, str]:
        """Trie-side conservation: every indexed block must be allocated
        in the pool with refcount >= 1 (its own trie hold), and node
        count must match the walk. With ``strict_idle`` (the health
        probe, when no streams are live) the converse holds too: the
        trie's holds are the ONLY holds, so every allocated pool block
        must be trie-indexed at refcount exactly 1 — anything else is a
        leaked stream ref. Returns (ok, detail)."""
        with self.pool._lock:
            problems = []
            seen = 0
            trie_blocks = set()
            stack = list(self.root.values())
            while stack:
                node = stack.pop()
                seen += 1
                trie_blocks.add(node.block)
                if self.pool._ref.get(node.block, 0) < 1:
                    problems.append(
                        f"trie block {node.block} not allocated")
                stack.extend(node.children.values())
            if seen != self._nodes:
                problems.append(f"node count drift: walked {seen}, "
                                f"tracked {self._nodes}")
            if strict_idle:
                stray = {b: r for b, r in self.pool._ref.items()
                         if b not in trie_blocks or r != 1}
                if stray:
                    problems.append(
                        f"idle pool holds {len(stray)} non-trie/"
                        f"over-held block(s) ({sorted(stray)[:4]}…)")
            return (not problems,
                    "; ".join(problems) if problems else
                    f"{seen} trie nodes consistent")

    def hit_rate(self) -> float:
        """Lifetime fraction of looked-up prompt tokens served from
        committed cache blocks (the ``serving.prefix_cache_hit_rate``
        gauge)."""
        return (self.hit_tokens / self.lookup_tokens
                if self.lookup_tokens else 0.0)

    def stats(self) -> dict:
        return {
            "nodes": self._nodes,
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "hit_rate": round(self.hit_rate(), 4),
            "evictions": self.evictions,
        }


def default_pool_blocks(batch_buckets, max_length: int,
                        block_size: int) -> int:
    """Default pool size: the largest decode batch bucket at full
    ``max_length`` context — the paged pool then NEVER sheds a batch the
    contiguous layout could have served (admission only bites when the
    operator deliberately sizes the pool below that, trading worst-case
    headroom for more concurrent typical-length streams)."""
    top = max(int(b) for b in batch_buckets) if batch_buckets else 32
    return top * math.ceil(max_length / block_size)
