"""Paged/block KV cache for the decode tier (docs/SERVING.md).

The contiguous r13 cache allocated ``(B, H, max_length, Dh)`` per layer for
every decode batch — every stream paid ``max_length`` positions of device
memory no matter how short its context, and the ceiling on concurrent
streams per device was ``pool_bytes / (max_length * per_token_bytes)``.
This module replaces that with the vLLM-style paged layout:

- **One slot-flat pool per layer** — ``(S, H, Dh)`` with
  ``S = (num_blocks + 1) * block_size`` token slots. Block 0 is the
  RESERVED TRASH BLOCK: every position outside a stream's reservation
  (bucket padding, padded batch rows) scatters there and every read is
  position-masked before the softmax, so trash content is never visible.
- **A page table per stream** — the host-side list of physical block ids
  backing logical positions ``[0, ceil((len + max_new) / block_size) *
  block_size)``. The decode executable takes the table as data
  ``(B, max_blocks)`` and expands it to per-position slot indices in-jit,
  so ONE executable (per batch bucket) serves every mix of context
  lengths with zero recompiles — context length is a value, not a shape.
- **All-or-nothing admission** — :meth:`BlockPool.reserve` either hands a
  batch every block its streams need for the WHOLE generation (prompt +
  ``max_new_tokens``, so a stream can never run out mid-decode) or raises
  :class:`PoolExhaustedError` with nothing allocated — the scheduler
  sheds the batch 429 + Retry-After (the r13 shed contract, new cause
  ``pool_exhausted``) instead of OOMing. Blocks free on completion/eos
  (the decode loop exits early once every live row has emitted eos) and
  on shed.

Rollback semantics (speculative decoding, serving/generate.py): rejected
window positions keep their reservation — rolling back is pure position
bookkeeping on the host — and their stale K/V rows are PROVABLY
overwritten before any read: the next window write covers ``[pos + m,
pos + m + w)`` ⊇ the rejected ``[pos + m, pos + w)`` (``m ≥ 1``), and
every attention read in between is masked to ``k_pos <= position``.

Gauges: ``serving.kv_pool_blocks_total`` / ``_free``,
``serving.concurrent_streams`` (+ per-pool high-water in :meth:`stats`),
the inputs to the ``concurrent_streams_per_device`` bench metric.
"""

from __future__ import annotations

import math
import threading
from typing import List, Sequence

import numpy as np

from deeplearning4j_tpu.serving.resilience import PoolExhaustedError
from deeplearning4j_tpu.util import telemetry as tm

__all__ = ["BlockPool", "PoolExhaustedError"]


class BlockPool:
    """Host-side allocator + device-side slot-flat K/V pools (module doc).

    ``num_blocks`` usable blocks of ``block_size`` token slots each; the
    device tensors carry one extra (trash) block at index 0. Device state
    lives in ``self.pools`` — one ``{"k": (S,H,Dh), "v": (S,H,Dh)}`` per
    transformer layer, created by the blocks' ``init_pool`` and donated
    through the decode executables (the generator threads the returned
    pools back)."""

    def __init__(self, blocks, *, block_size: int, num_blocks: int,
                 max_length: int, model_id: str = "",
                 dtype=None):
        import jax.numpy as jnp

        if block_size < 1 or num_blocks < 1:
            raise ValueError("block_size and num_blocks must be >= 1")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_length = int(max_length)
        self.model_id = str(model_id)
        #: page-table width: enough blocks to map every logical position
        self.max_blocks_per_stream = math.ceil(self.max_length
                                               / self.block_size)
        self.num_slots = (self.num_blocks + 1) * self.block_size
        self.pools = [blk.init_pool(self.num_slots,
                                    dtype or jnp.float32)
                      for blk in blocks]
        self._lock = threading.Lock()
        # block 0 is the trash block — never handed out
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        self._streams = 0
        self.peak_streams = 0
        self._gauges()

    # ---------------------------------------------------------- accounting
    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Blocks one stream needs for its WHOLE generation."""
        return math.ceil((prompt_len + max_new) / self.block_size)

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def bytes_per_token(self) -> int:
        """Device bytes one token slot costs across every layer (K + V).
        Pure shape arithmetic — this runs on every stats poll, and
        slicing (``p["k"][0]``) would dispatch an eager device gather per
        layer just to read sizes."""
        return sum(int(p["k"].nbytes // p["k"].shape[0]
                       + p["v"].nbytes // p["v"].shape[0])
                   for p in self.pools)

    def pool_bytes(self) -> int:
        """Total device bytes of the usable pool (trash block excluded)."""
        return self.num_blocks * self.block_size * self.bytes_per_token()

    def contiguous_stream_ceiling(self) -> int:
        """How many streams the SAME bytes hold under the r13 contiguous
        layout (every stream pays ``max_length`` slots) — the baseline the
        ``concurrent_streams_per_device`` gate must beat."""
        return (self.num_blocks * self.block_size) // self.max_length

    def _gauges(self):
        tm.gauge("serving.kv_pool_blocks_total", self.num_blocks,
                 model=self.model_id)
        tm.gauge("serving.kv_pool_blocks_free", len(self._free),
                 model=self.model_id)
        tm.gauge("serving.concurrent_streams", self._streams,
                 model=self.model_id)

    # ----------------------------------------------------------- admission
    def reserve(self, counts: Sequence[int]) -> List[List[int]]:
        """All-or-nothing: allocate ``counts[i]`` blocks for stream i, or
        raise :class:`PoolExhaustedError` having allocated NOTHING."""
        need = int(sum(counts))
        with self._lock:
            if need > len(self._free):
                tm.counter("serving.pool_exhausted_total",
                           model=self.model_id)
                raise PoolExhaustedError(
                    f"{self.model_id or 'paged-kv'}: batch needs {need} "
                    f"KV blocks, pool has {len(self._free)} free "
                    f"(of {self.num_blocks})")
            out = []
            for c in counts:
                out.append([self._free.pop() for _ in range(int(c))])
            self._streams += len(counts)
            self.peak_streams = max(self.peak_streams, self._streams)
            self._gauges()
            return out

    def release(self, tables: Sequence[Sequence[int]]):
        """Return every stream's blocks to the free list (eos / batch done
        / shed rollback)."""
        with self._lock:
            for t in tables:
                self._free.extend(int(b) for b in t)
            self._streams = max(0, self._streams - len(list(tables)))
            self._gauges()

    # ------------------------------------------------------------ programs
    def table_array(self, tables: Sequence[Sequence[int]],
                    batch: int) -> np.ndarray:
        """Page tables as the decode executable's (B, max_blocks) int32
        input — unallocated entries (and padded batch rows) point at the
        trash block (0)."""
        out = np.zeros((batch, self.max_blocks_per_stream), np.int32)
        for i, t in enumerate(tables):
            out[i, :len(t)] = np.asarray(t, np.int32)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "block_size": self.block_size,
                "blocks_total": self.num_blocks,
                "blocks_free": len(self._free),
                "streams": self._streams,
                "peak_streams": self.peak_streams,
                "pool_bytes": self.pool_bytes(),
                "contiguous_stream_ceiling":
                    self.contiguous_stream_ceiling(),
            }


def default_pool_blocks(batch_buckets, max_length: int,
                        block_size: int) -> int:
    """Default pool size: the largest decode batch bucket at full
    ``max_length`` context — the paged pool then NEVER sheds a batch the
    contiguous layout could have served (admission only bites when the
    operator deliberately sizes the pool below that, trading worst-case
    headroom for more concurrent typical-length streams)."""
    top = max(int(b) for b in batch_buckets) if batch_buckets else 32
    return top * math.ceil(max_length / block_size)
