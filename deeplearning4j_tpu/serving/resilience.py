"""Serving resilience layer: failure semantics for the model server
(docs/SERVING.md#resilience).

The r13 serving tier proved the performance contracts (bit-identical
batching, zero steady-state recompiles, priority lanes); this module makes
the tier survive the failures production actually sees, applying r11's
standard — every fault kind has its specific recovery asserted in CI
(benchmarks/resilience_smoke.py) — to the serving path:

- **The shed-error hierarchy** — every way a request can be refused,
  each mapping to one HTTP status the server translates mechanically:
  queue-full/deadline (429), draining (503), circuit-open (503 +
  ``Retry-After`` = the breaker's remaining cooldown), brownout (429),
  dead worker (503). Defined HERE (scheduler.py re-exports them) so the
  breaker/brownout machinery never imports the scheduler.
- **:class:`CircuitBreaker`** — one per model (``BatchScheduler`` owns
  it): consecutive-error or windowed error-rate thresholds OPEN it, after
  which submits fast-fail with :class:`CircuitOpenError` instead of
  queueing work into a model that is failing every batch (doomed work
  holds queue slots, burns device time, and turns one broken model into
  whole-tier latency). After ``cooldown_s`` the breaker goes HALF-OPEN:
  a bounded number of probe requests pass; the first probe batch's
  outcome closes it (success) or re-opens it (failure). The state
  machine is the classic three-state breaker; the clock is injectable so
  tests drive transitions without sleeping.
- **:class:`BrownoutController`** — degraded service before hard
  failure: when the r17 SLO engine (util/slo.py) reports error-budget
  exhaustion, the controller sheds the ``batch`` lane across the
  router's models while ``interactive`` keeps serving — bulk work is
  the load you can shed without breaking a promise; budget recovery
  restores it. Lanes shed in declared order, never ``interactive``
  first.
- **Worker-crash semantics** — :class:`WorkerCrashedError` is what the
  supervised scheduler worker (scheduler.py watchdog) sets on the
  in-flight batch's futures when the worker loop dies: the caller gets a
  loud 500, the flight recorder gets the cause, and the worker restarts
  under ``RetryPolicy`` backoff; ``max_restarts`` exhausted flips the
  model's ``serving.worker.<id>`` health check and fails all queued
  futures with :class:`SchedulerStoppedError` instead of letting them
  hang on a dead queue forever.
- **Reload rejection** — :class:`ModelLoadError` (archive unreadable /
  corrupt: the load never partially registers) and
  :class:`ReloadRejectedError` (structure mismatch, warmup failure, or
  a failed canary — NaN-producing weights never reach traffic; the old
  version keeps serving). Raised by ``ModelRouter.load/reload``
  (router.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

from deeplearning4j_tpu.util import telemetry as tm

# ------------------------------------------------------------- shed errors
# (scheduler.py re-exports these names; serving/__init__.py exports them)


class ShedError(RuntimeError):
    """Request rejected by load shedding (HTTP 429 + Retry-After)."""

    http_status = 429
    retry_after_s = 1.0
    #: shed-counter/flight-recorder cause when the shed is raised from the
    #: EXECUTE path (model.execute inside a batch cycle) rather than at
    #: submit time — subclasses with an execute-time path override it
    shed_reason = "shed"


class QueueFullError(ShedError):
    """Admission control: the model's queue is at capacity."""


class DeadlineExceededError(ShedError):
    """The request's queueing deadline expired before execution started."""


class PoolExhaustedError(ShedError):
    """The model's paged KV block pool cannot hold this batch's streams
    (serving/paged.py): decode admission sheds with 429 + Retry-After
    instead of OOMing the device. Raised BEFORE any device work — the
    reserved blocks are rolled back, nothing leaks. Its flight-recorder
    cause and per-lane shed counter are first-class (``pool_exhausted``),
    the r13 shed contract with a new cause."""

    shed_reason = "pool_exhausted"


class SchedulerDrainingError(ShedError):
    """The scheduler is draining (SIGTERM) — no new work accepted."""

    http_status = 503


class SchedulerStoppedError(ShedError):
    """The scheduler's worker is permanently gone (shut down, or crashed
    past its restart budget): submit fails fast instead of enqueueing into
    a dead queue where the future would hang forever."""

    http_status = 503


class CircuitOpenError(ShedError):
    """The model's circuit breaker is open: fast-fail instead of queueing
    doomed work. ``retry_after_s`` is the breaker's remaining cooldown."""

    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.1, float(retry_after_s))


class BrownoutShedError(ShedError):
    """The lane is browned out (SLO error budget exhausted): bulk work is
    shed so the interactive promise survives."""


class FleetUnavailableError(ShedError):
    """The fleet's hash ring is empty — every worker is dead, unhealthy,
    or draining (serving/fleet.py). The front tier answers 503 +
    ``Retry-After``: workers respawn under :data:`FLEET_RESPAWN_POLICY`,
    so the condition is expected to clear."""

    http_status = 503
    retry_after_s = 2.0


class WorkerProxyError(RuntimeError):
    """Every failover attempt to proxy a request hit a connection-level
    failure (refused / reset / truncated response) — the fleet router's
    502. HTTP-level errors from a worker (429/503/…) are NOT this: they
    relay verbatim; only transport failures fail over and, exhausted,
    become a 502."""


class WorkerCrashedError(RuntimeError):
    """Set on the in-flight batch's futures when the scheduler worker loop
    dies — the HTTP 500 path (a crash is a server error, not a shed)."""


class ModelLoadError(RuntimeError):
    """A model archive failed to load cleanly (corrupt/truncated zip,
    structure mismatch with its own config). ``ModelRouter.load`` raises
    this WITHOUT registering anything; ``reload`` raises it with the old
    version still serving. ``__cause__`` carries the underlying error."""


class ReloadRejectedError(RuntimeError):
    """A rolling reload was rejected before the swap — canary failure,
    warmup failure, or parameter-structure mismatch. The old weights keep
    serving; nothing about the live model changed."""


# ------------------------------------------------------- fleet supervision

#: backoff for respawning a dead fleet worker process (serving/fleet.py
#: supervisor) — the scheduler-watchdog convention (WORKER_RESTART_POLICY)
#: lifted to process scope: exponential + jitter so N workers dying at
#: once (an OOM-killer sweep) do not respawn in lockstep, capped so a
#: crash-looping worker settles at one attempt every few seconds while
#: the rest of the ring keeps serving.
def _fleet_respawn_policy():
    from deeplearning4j_tpu.util.faults import RetryPolicy

    return RetryPolicy(max_attempts=8, base_delay=0.2, multiplier=2.0,
                       max_delay=5.0, jitter=0.25)


FLEET_RESPAWN_POLICY = _fleet_respawn_policy()


# --------------------------------------------------------- circuit breaker

#: breaker states, also exported as the ``serving.breaker_state`` gauge
#: (0 = closed, 1 = half_open, 2 = open)
BREAKER_STATES = ("closed", "half_open", "open")


class CircuitBreaker:
    """Per-model three-state circuit breaker (see module docstring).

    Outcomes are recorded per BATCH (the scheduler's unit of compute
    failure — one broken batch fails every rider). Trip conditions, both
    evaluated on ``record_error``:

    - ``consecutive_errors`` failed batches in a row, or
    - error fraction over the last ``window`` batches ≥ ``error_rate``
      once at least ``min_samples`` batches are in the window.

    ``allow()`` is the submit-time gate: a no-op while closed, raises
    :class:`CircuitOpenError` while open (``Retry-After`` = remaining
    cooldown), and while half-open admits up to ``half_open_probes``
    requests whose batch outcome decides the next state. ``clock`` is
    injectable (tests drive the cooldown without sleeping).
    """

    def __init__(self, *, consecutive_errors: int = 3,
                 error_rate: float = 0.5, window: int = 16,
                 min_samples: int = 8, cooldown_s: float = 5.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 model_id: str = ""):
        self.consecutive_errors = int(consecutive_errors)
        self.error_rate = float(error_rate)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self.clock = clock
        self.model_id = model_id
        self._lock = threading.Lock()
        self.state = "closed"
        self._outcomes: deque = deque(maxlen=self.window)  # 1 = error
        self._consecutive = 0
        self._opened_at = 0.0
        self._probes_left = 0
        self._half_open_at = 0.0
        self.opens = 0
        self.closes = 0

    # ------------------------------------------------------------- recording
    def _trip_locked(self, now: float, why: str):
        self.state = "open"
        self._opened_at = now
        self._probes_left = 0
        self.opens += 1
        tm.counter("serving.breaker_opens_total", model=self.model_id)
        tm.instant("serving.breaker_open", model=self.model_id, reason=why)

    def record_error(self):
        now = self.clock()
        with self._lock:
            if self.state == "half_open":
                # the probe failed: the fault is still there — back to open
                # for a fresh cooldown
                self._trip_locked(now, "half_open_probe_failed")
                return
            if self.state == "open":
                return
            self._outcomes.append(1)
            self._consecutive += 1
            if self._consecutive >= self.consecutive_errors:
                self._trip_locked(
                    now, f"{self._consecutive} consecutive errors")
                return
            if len(self._outcomes) >= self.min_samples:
                frac = sum(self._outcomes) / len(self._outcomes)
                if frac >= self.error_rate:
                    self._trip_locked(
                        now, f"error rate {frac:.2f} over "
                        f"{len(self._outcomes)} batches")

    def record_success(self):
        with self._lock:
            if self.state == "half_open":
                # the probe batch succeeded: the model recovered
                self.state = "closed"
                self._outcomes.clear()
                self._consecutive = 0
                self._probes_left = 0
                self.closes += 1
                tm.counter("serving.breaker_closes_total",
                           model=self.model_id)
                tm.instant("serving.breaker_close", model=self.model_id)
                return
            self._outcomes.append(0)
            self._consecutive = 0

    # ------------------------------------------------------------ admission
    def allow(self):
        """Submit-time gate: raise :class:`CircuitOpenError` unless this
        request may enter the queue (closed, or an admitted half-open
        probe)."""
        with self._lock:
            if self.state == "closed":
                return
            now = self.clock()
            if self.state == "open":
                remaining = self.cooldown_s - (now - self._opened_at)
                if remaining > 0:
                    raise CircuitOpenError(
                        f"{self.model_id}: circuit open "
                        f"({remaining:.1f}s cooldown left)",
                        retry_after_s=remaining)
                self.state = "half_open"
                self._probes_left = self.half_open_probes
                self._half_open_at = now
                tm.instant("serving.breaker_half_open", model=self.model_id)
            # half_open: admit bounded probes; everyone else waits for the
            # probes' verdict rather than piling onto a maybe-broken model
            if self._probes_left <= 0:
                # an admitted probe can die WITHOUT a batch outcome (shed
                # at the queue, deadline-expired while queued): after one
                # cooldown with no verdict, re-arm the probes — a lost
                # probe must not wedge the breaker half-open forever
                if now - self._half_open_at >= self.cooldown_s:
                    self._probes_left = self.half_open_probes
                    self._half_open_at = now
                else:
                    raise CircuitOpenError(
                        f"{self.model_id}: circuit half-open, probe in "
                        "flight", retry_after_s=1.0)
            self._probes_left -= 1

    # --------------------------------------------------------------- queries
    def state_value(self) -> int:
        return BREAKER_STATES.index(self.state)

    def status(self) -> dict:
        with self._lock:
            recent = list(self._outcomes)
            return {
                "state": self.state,
                "consecutive_errors": self._consecutive,
                "recent_error_fraction": round(
                    sum(recent) / len(recent), 4) if recent else 0.0,
                "opens": self.opens,
                "closes": self.closes,
                "cooldown_s": self.cooldown_s,
            }


# --------------------------------------------------------------- brownout


class BrownoutController:
    """SLO-budget-exhaustion → lane brownout (see module docstring).

    ``install()`` hooks the process SLO engine's breach/recovery
    callbacks (util/slo.py). While ANY objective's budget is exhausted,
    every scheduler in ``router`` sheds ``shed_lanes`` (default: the
    ``batch`` lane — bulk work first, ``interactive`` never) with
    :class:`BrownoutShedError`; when the last exhausted objective
    recovers, the lanes reopen. Idempotent across repeated breaches of
    the same objective.
    """

    def __init__(self, router, shed_lanes: Iterable[str] = ("batch",)):
        self.router = router
        self.shed_lanes = tuple(shed_lanes)
        if "interactive" in self.shed_lanes:
            raise ValueError(
                "brownout must not shed the interactive lane — it exists "
                "to protect it (shed_lanes order: batch before interactive)")
        self._lock = threading.Lock()
        self._exhausted: set = set()
        self.active = False
        self._installed = False

    def install(self) -> "BrownoutController":
        from deeplearning4j_tpu.util import slo

        if not self._installed:
            eng = slo.get_engine()
            eng.on_breach(self._on_breach)
            eng.on_recover(self._on_recover)
            self._installed = True
        return self

    def uninstall(self) -> "BrownoutController":
        """Detach from the process SLO engine and restore the lanes. The
        engine holds strong references to the hooks (→ this controller →
        the router and its weights); a long-lived process that builds
        routers repeatedly must uninstall, or later breaches act on
        shut-down routers kept alive by the hook list."""
        from deeplearning4j_tpu.util import slo

        if self._installed:
            eng = slo.get_engine()
            eng.off_breach(self._on_breach)
            eng.off_recover(self._on_recover)
            self._installed = False
        with self._lock:
            self._exhausted.clear()
            if self.active:
                self._apply(False)
        return self

    def _apply(self, active: bool):
        self.active = active
        self.router.set_brownout(self.shed_lanes if active else ())
        tm.gauge("serving.brownout_active", 1.0 if active else 0.0)
        if active:
            tm.counter("serving.brownouts_total")
            tm.instant("serving.brownout_start",
                       lanes=",".join(self.shed_lanes))
        else:
            tm.instant("serving.brownout_end")

    def _on_breach(self, name: str, detail: str):
        with self._lock:
            first = not self._exhausted
            self._exhausted.add(name)
            if first:
                self._apply(True)

    def _on_recover(self, name: str):
        with self._lock:
            self._exhausted.discard(name)
            if self.active and not self._exhausted:
                self._apply(False)

    def status(self) -> dict:
        with self._lock:
            return {"active": self.active,
                    "shed_lanes": list(self.shed_lanes),
                    "exhausted_objectives": sorted(self._exhausted)}
