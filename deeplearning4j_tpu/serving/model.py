"""ServingModel — one loaded model behind the batching scheduler.

Binds a trained ``MultiLayerNetwork``/``ComputationGraph`` (or a mesh-backed
``ParallelInference``) to the serving tier with ONE
:class:`~deeplearning4j_tpu.data.bucketing.BucketingPolicy` as the shared
source of truth for every shape decision — warmup, coalescing limit,
request padding, and prefill/decode buckets all read the same policy, so a
request size that "falls between buckets" pads up to the next bucket
instead of tracing a new program (docs/SERVING.md).

Two kinds:

- ``kind="classify"``: forward inference. Requests are (n, …feature) row
  batches; the scheduler's coalesced rows are chunk-planned
  (``plan_serving_batch``) and executed through the AOT-warmed
  ``net.output`` path (or ``ParallelInference.output`` when ``use_mesh``),
  then split back per request. Row independence makes the batched result
  bit-identical to per-request results.
- ``kind="generate"``: paged-KV-cache autoregressive decode
  (serving/generate.py — paged block pool, optional speculative decoding
  via ``draft_net``/``spec_tokens``, optional ``quantize="int8"``).
  Requests are token prompts; coalesced prompts decode as one batch,
  per-request ``max_new_tokens`` honored by trimming (rows are
  attention-independent, so batching never changes a row's tokens). A
  batch the block pool cannot hold sheds ``PoolExhaustedError`` (429).

``quantize="int8"`` on either kind serves resident int8 weights +
per-channel scales with the dequantize inside the forward
(serving/quantize.py); the fp32 path is bit-unchanged.

``execute`` counts the XLA traces it causes via the CompileWatcher — the
scheduler publishes them as ``serving.recompiles_total``, the steady-state-
zero contract the CI smoke asserts.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import get_watcher

_DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


class ServingModel:
    """One model-id's executor (see module doc)."""

    def __init__(self, net, model_id: str, *, kind: str = "classify",
                 bucketing=None, use_mesh: bool = False,
                 export_dir: Optional[str] = None,
                 max_length: Optional[int] = None,
                 prefill_buckets=None,
                 paged: bool = True, block_size: int = 16,
                 pool_blocks: Optional[int] = None,
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 draft_net=None, spec_tokens: int = 4,
                 quantize: Optional[str] = None):
        if kind not in ("classify", "generate"):
            raise ValueError(f"unknown serving kind {kind!r}")
        self.net = net
        self.model_id = str(model_id)
        self.kind = kind
        self.export_dir = export_dir
        self._max_length = max_length
        self._use_mesh = bool(use_mesh)
        # decode-engine knobs (docs/SERVING.md#paged-kv--speculative-decode)
        self._paged = bool(paged)
        self._block_size = int(block_size)
        self._pool_blocks = pool_blocks
        self._prefix_cache = bool(prefix_cache)
        self._prefill_chunk = prefill_chunk
        self._draft_net = draft_net
        self._spec_tokens = int(spec_tokens)
        self.quantize = quantize
        self._qp = None       # classify-kind int8 residents
        self._qforward = None
        #: rolling-reload version surface (docs/SERVING.md#resilience):
        #: starts at 1, bumps on every successful swap_from()
        self.version = 1
        self.reload_time: Optional[float] = None
        # execute() holds this for each batch; a rolling reload's swap takes
        # it too, so the swap lands BETWEEN batch cycles — the in-flight
        # batch finishes on the old weights, the next one runs the new.
        # REENTRANT: a chunked prefill's yield hook re-enters execute()
        # from the same worker thread to run queued decode batches
        # between prompt chunks (serving/scheduler.py).
        self._swap_lock = threading.RLock()
        if isinstance(bucketing, str):
            bucketing = BucketingPolicy.from_spec(bucketing)
        if bucketing is None:
            bucketing = BucketingPolicy.from_conf(getattr(net, "conf", None))
        if bucketing is None or not isinstance(
                bucketing.batch_buckets, tuple):
            # serving needs a FINITE bucket list (warmup must enumerate it);
            # keep any seq buckets the conf declared
            seq = getattr(bucketing, "seq_buckets", None)
            bucketing = BucketingPolicy(batch_buckets=_DEFAULT_BUCKETS,
                                        seq_buckets=seq)
        self.policy = bucketing
        self.inference = None
        self.generator = None
        if kind == "generate":
            from deeplearning4j_tpu.serving.generate import Generator

            self.generator = Generator(
                net, max_length=max_length,
                batch_buckets=self.policy.batch_buckets,
                prefill_buckets=(prefill_buckets
                                 or self.policy.seq_buckets),
                paged=self._paged, block_size=self._block_size,
                pool_blocks=self._pool_blocks,
                prefix_cache=self._prefix_cache,
                prefill_chunk=self._prefill_chunk,
                draft_net=self._draft_net, spec_tokens=self._spec_tokens,
                quantize=quantize, model_id=self.model_id)
            self.policy = self.generator.policy
            self._qp = self.generator._qp
        elif quantize is not None:
            if use_mesh:
                raise ValueError("quantize + use_mesh is not supported — "
                                 "the mesh path shards fp32 params")
            from deeplearning4j_tpu.serving.quantize import maybe_quantize
            from deeplearning4j_tpu.util.compile_watcher import note_trace

            self._qp = maybe_quantize(net, quantize,
                                      model_id=self.model_id)
            fwd, qp = net.make_forward_fn(), self._qp

            def _qfwd(raw, states, x):
                # the int8 classify executable: dequantize-in-forward over
                # the resident (int8, scales) leaves (serving/quantize.py)
                note_trace("serving.classify_int8", x)
                return fwd(qp.rebuild(raw), states, x)

            import jax

            self._qforward = jax.jit(_qfwd)
        if use_mesh and kind != "generate":
            from deeplearning4j_tpu.parallel.wrapper import ParallelInference

            # the SAME policy object the scheduler plans with — one bucket
            # source of truth for warmup() and coalescing
            self.inference = ParallelInference(net, bucketing=self.policy)
        self.warmed = False

    @property
    def supports_chunked_prefill(self) -> bool:
        """Whether this model's batches can yield mid-prefill — the
        scheduler only wires its interleave hook into models that chunk
        (one whole-prompt prefill has no yield points)."""
        return (self.generator is not None
                and self.generator.prefill_chunk is not None)

    # -------------------------------------------------------------- shapes
    def coalesce_limit(self) -> int:
        """Largest batch the scheduler should coalesce to — the largest
        bucket (a bigger batch would just be split again)."""
        top = self.policy.largest_batch_bucket()
        return int(top) if top else 64

    def payload_rows(self, payload) -> int:
        if self.kind == "generate":
            return 1  # one prompt row per request
        return int(np.shape(payload)[0])

    # -------------------------------------------------------------- warmup
    def warmup(self) -> int:
        """Compile every bucket signature before traffic: the classify
        forward per batch bucket (through the r8 AOT path — with
        ``export_dir`` a warm process deserializes the stored lowering
        instead of re-tracing), or every prefill/decode executable for
        generate. Returns the number of signatures primed."""
        if self.kind == "generate":
            primed = self.generator.warmup()
        elif self._qforward is not None:
            conf = getattr(self.net, "conf", None)
            shape = tuple(getattr(conf, "input_shape", None) or ())
            if not shape:
                raise ValueError(
                    f"{self.model_id}: warmup() needs conf.input_shape")
            raw = self._qp.args()
            primed = 0
            for b in self.policy.batch_buckets:
                self._qforward(raw, self.net.states,
                               np.zeros((int(b),) + shape, np.float32))
                primed += 1
        elif self.inference is not None:
            primed = self.inference.warmup(
                batch_sizes=self.policy.batch_buckets)
        else:
            conf = getattr(self.net, "conf", None)
            shape = tuple(getattr(conf, "input_shape", None) or ())
            if not shape:
                raise ValueError(
                    f"{self.model_id}: warmup() needs conf.input_shape")
            primed = self.net.warmup(
                shapes=[(int(b),) + shape
                        for b in self.policy.batch_buckets],
                train=False, inference=True, export_dir=self.export_dir)
            # prime the jit dispatch too (output() prefers AOT executables,
            # but a signature miss must still find a warm jit cache)
            for b in self.policy.batch_buckets:
                self.net.output(np.zeros((int(b),) + shape, np.float32))
        self.warmed = True
        return primed

    # ------------------------------------------------------------- execute
    def execute(self, payloads: List[Any], _trace: bool = False,
                _step: Optional[int] = None, _yield=None, **opts
                ) -> Tuple[List[Any], Dict[str, Any]]:
        """Run one coalesced batch; returns (per-payload results, stats).
        stats: real/padded row counts and the number of XLA traces this
        batch caused (0 in steady state); generate batches add
        ``decode_tokens``/``decode_seconds`` for per-request tokens/sec.
        ``_trace`` (set by the scheduler for head-sampled batches) emits
        the batch-level pad/device/decode phase spans; ``_step`` is the
        scheduler's batch-cycle number — the serving faults' ``@nth``
        concept (util/faults.py). The faults fire ONLY on scheduler
        batches (``_step`` set): a reload's canary and direct execute()
        calls run with ``_step=None``, and letting them consume a stepless
        armed fault would reject a good reload while the fault's
        documented target — the live worker — never saw it."""
        if _step is not None:
            injector = fl.get_injector()
            fault = injector.fire(fl.SERVING_SLOW_BATCH, step=_step)
            if fault is not None:
                # a real stall on the real worker thread: queued requests
                # behind it age toward their deadlines exactly as they
                # would behind a wedged device
                time.sleep((fault.arg or 50.0) / 1e3)
            if injector.fire(fl.SERVING_COMPUTE_ERROR,
                             step=_step) is not None:
                raise RuntimeError(
                    f"{self.model_id}: injected serving compute error "
                    f"(batch {_step})")
        watcher = get_watcher()
        with self._swap_lock:
            # traces are counted per-THREAD: a concurrent reload warming
            # its shadow model on another thread must not read as this
            # batch having recompiled (docs/SERVING.md#resilience)
            traces_before = watcher.thread_traces()
            stats: Dict[str, Any] = {}
            if self.kind == "generate":
                results, real, padded = self._execute_generate(
                    payloads, _trace=_trace, _stats=stats, _yield=_yield,
                    **opts)
            else:
                results, real, padded = self._execute_classify(
                    payloads, _trace=_trace, **opts)
            stats.update({
                "real_rows": real,
                "padded_rows": padded,
                "recompiles": watcher.thread_traces() - traces_before,
            })
        return results, stats

    def _emit(self, name: str, t0_ns: int, **args):
        # deferred (no registry lock): this runs on the scheduler worker
        # while other models' workers hold the GIL — see event_deferred
        tm.get_telemetry().event_deferred(name, t0_ns, time.time_ns(),
                                          model=self.model_id, **args)

    def _execute_classify(self, payloads, _trace=False, **opts):
        if opts:
            raise ValueError(f"classify takes no options, got {opts}")
        n = sum(int(np.shape(p)[0]) for p in payloads)
        # the SAME cap-aware plan the mesh path executes, so the occupancy
        # stat reflects the padding that actually ran (mesh-divisibility
        # rounding of the 'data' axis is not included — on a 1-device
        # serving mesh it is zero)
        cap = (self.inference.batch_limit if self.inference is not None
               else None)
        plan = self.policy.plan_serving_batch(n, cap=cap)
        padded = sum(p for _, p in plan)
        if self.inference is not None:
            t0 = time.time_ns() if _trace else 0
            xs = np.concatenate([np.asarray(p) for p in payloads], axis=0)
            if _trace:
                self._emit("serving.exec.pad", t0, rows=n)
            t1 = time.time_ns() if _trace else 0
            out = self.inference.output(xs)  # plans the chunks inside
            if _trace:
                self._emit("serving.exec.device", t1, rows=n, padded=padded)
        else:
            # bucket-padding phase (host work) separated from the device
            # phase so a sampled trace shows where the milliseconds went
            t0 = time.time_ns() if _trace else 0
            xs = np.concatenate([np.asarray(p) for p in payloads], axis=0)
            padded_chunks, off = [], 0
            for take, bucket in plan:
                chunk = xs[off:off + take]
                if bucket != take:
                    pad = np.zeros((bucket - take,) + xs.shape[1:],
                                   xs.dtype)
                    chunk = np.concatenate([chunk, pad], axis=0)
                padded_chunks.append((chunk, take))
                off += take
            if _trace:
                self._emit("serving.exec.pad", t0, rows=n, padded=padded)
            t1 = time.time_ns() if _trace else 0
            if self._qforward is not None:
                raw = self._qp.args()
                chunks = [np.asarray(self._qforward(
                    raw, self.net.states, chunk))[:take]
                          for chunk, take in padded_chunks]
            else:
                chunks = [np.asarray(self.net.output(chunk))[:take]
                          for chunk, take in padded_chunks]
            out = np.concatenate(chunks, axis=0)
            if _trace:
                self._emit("serving.exec.device", t1, rows=n,
                           padded=padded, chunks=len(plan))
        results, off = [], 0
        for p in payloads:
            k = int(np.shape(p)[0])
            results.append(out[off:off + k])
            off += k
        return results, n, padded

    def _execute_generate(self, payloads, _trace=False, _stats=None,
                          _yield=None, **opts):
        prompts = [list(np.asarray(p).ravel().astype(np.int64)) for p in
                   payloads]
        max_new = int(opts.get("max_new_tokens", 16))
        t0 = time.perf_counter()
        tokens = self.generator.generate(
            prompts, max_new_tokens=max_new,
            temperature=float(opts.get("temperature", 0.0)),
            eos_id=opts.get("eos_id"), trace=_trace,
            stats=_stats,  # speculation: draft_accept_rate per rider
            yield_hook=_yield)  # chunked prefill: scheduler interleave
        if _stats is not None:
            # decode wall (incl. prefill) — the scheduler turns this into
            # per-request serving.decode_tokens_per_sec observations
            _stats["decode_seconds"] = time.perf_counter() - t0
            _stats["decode_tokens"] = sum(len(t) for t in tokens)
        real = len(prompts)
        padded = self.policy.bucket_batch(real)
        return tokens, real, padded

    # ----------------------------------------------------- rolling reload
    def clone_with_net(self, net) -> "ServingModel":
        """A SHADOW ServingModel around ``net`` with this model's exact
        serving configuration (kind, bucket policy, export dir, mesh) —
        the reload pipeline warms and canary-validates it without touching
        the live model's caches (docs/SERVING.md#resilience)."""
        return ServingModel(net, self.model_id, kind=self.kind,
                            bucketing=self.policy,
                            use_mesh=self._use_mesh,
                            export_dir=self.export_dir,
                            max_length=self._max_length,
                            paged=self._paged,
                            block_size=self._block_size,
                            pool_blocks=self._pool_blocks,
                            prefix_cache=self._prefix_cache,
                            prefill_chunk=self._prefill_chunk,
                            draft_net=self._draft_net,
                            spec_tokens=self._spec_tokens,
                            quantize=self.quantize)

    def structure_matches(self, net) -> bool:
        """Whether ``net``'s parameter tree is swap-compatible with the
        live one (same treedef, same leaf shapes) — a reload that changes
        topology must go through a fresh ``register()``, not a swap."""
        import jax

        live = jax.tree_util.tree_leaves(self.net.params)
        new = jax.tree_util.tree_leaves(net.params)
        if (jax.tree_util.tree_structure(self.net.params)
                != jax.tree_util.tree_structure(net.params)):
            return False
        return all(np.shape(a) == np.shape(b) for a, b in zip(live, new))

    def canary_check(self, payload=None) -> Tuple[bool, str]:
        """Run one canary batch through THIS model (a warmed shadow during
        reload) and decide whether the weights are servable: the forward
        must complete and produce finite values. Corrupt or NaN-producing
        weights fail here and never reach traffic. Returns (ok, detail)."""
        try:
            if self.kind == "generate":
                if not self.generator.health_probe():
                    return False, "non-finite prefill logits"
                toks, _ = self.execute(
                    [np.asarray([1, 2, 3], np.int32)]
                    if payload is None else [payload], max_new_tokens=2)
                if not toks or not toks[0]:
                    return False, "canary decode produced no tokens"
            else:
                if payload is None:
                    conf = getattr(self.net, "conf", None)
                    shape = tuple(getattr(conf, "input_shape", None) or ())
                    if not shape:
                        return False, "no canary payload and no input_shape"
                    payload = np.zeros((1,) + shape, np.float32)
                out, _ = self.execute([payload])
                arr = np.asarray(out[0])
                if not np.all(np.isfinite(arr)):
                    bad = int(arr.size - np.isfinite(arr).sum())
                    return False, (f"canary output has {bad} non-finite "
                                   f"value(s) of {arr.size}")
        except Exception as e:  # noqa: BLE001 — canary verdict, not a crash
            return False, f"canary raised {type(e).__name__}: {e}"
        return True, ""

    def swap_from(self, shadow: "ServingModel") -> int:
        """Atomically adopt the shadow's (warmed, canary-validated) net and
        executors. Taken under the same lock ``execute`` holds, so the swap
        lands between batch cycles: zero shed requests, and — because the
        shadow warmed every bucket signature on its own thread — zero
        steady-state recompiles after it. Returns the new version."""
        with self._swap_lock:
            self.net = shadow.net
            self.generator = shadow.generator
            self.inference = shadow.inference
            # int8 residents swap WITH the net: the classify executable
            # branches on _qforward (whose closure holds the quantized
            # leaves) — leaving the old pair here would silently keep
            # serving the PRE-reload weights while version advances
            self._qp = shadow._qp
            self._qforward = shadow._qforward
            self.policy = shadow.policy
            self.warmed = shadow.warmed
            self.version += 1
            self.reload_time = time.time()
        tm.gauge("serving.model_version", self.version, model=self.model_id)
        return self.version

    def describe(self) -> dict:
        out = {
            "kind": self.kind,
            "buckets": self.policy.to_spec(),
            "coalesce_limit": self.coalesce_limit(),
            "warmed": self.warmed,
            "version": self.version,
            "reload_time": self.reload_time,
            "iteration": int(getattr(self.net, "iteration", 0) or 0),
            "mesh": self.inference is not None,
            "params": int(self.net.num_params())
            if hasattr(self.net, "num_params") else None,
        }
        if self.quantize:
            out["quantize"] = self.quantize
            if self._qp is not None:
                out["weight_bytes_resident"] = self._qp.resident_bytes()
                out["weight_bytes_fp32"] = self._qp.fp32_bytes()
        if self.generator is not None:
            pool = self.generator.pool_stats()
            if pool is not None:
                out["kv_pool"] = pool
            hit = self.generator.prefix_hit_rate()
            if hit is not None:
                # top-level so the fleet router's /v1/models poll reads it
                # without unpacking kv_pool (docs/SERVING.md#fleet)
                out["prefix_hit_rate"] = hit
            if self.generator.draft is not None:
                out["speculative"] = {
                    "spec_tokens": self.generator.spec_tokens,
                    "draft_params": int(
                        self.generator.draft.net.num_params())
                    if hasattr(self.generator.draft.net, "num_params")
                    else None,
                }
        return out
