"""Weight-only int8 serving (docs/SERVING.md#paged-kv--speculative-decode).

The serving twin of the r14 master-weight machinery, inverted: training
keeps fp32 masters and computes low-precision; serving keeps the fp32
archive as the master and holds RESIDENT int8 weights + per-channel fp32
scales on the device, dequantizing INSIDE the forward (one multiply per
weight, fusable into the consuming GEMM — the cuDNN reduced-precision
framing, arXiv:1410.0759). Riding the registered
``quantize_per_channel`` / ``dequantize_per_channel`` ops
(ops/compression.py).

What quantizes: floating leaves with ``ndim >= 2`` and at least
``min_size`` elements (weight matrices, embedding tables). Biases,
LayerNorm vectors and scalars pass through untouched — they are a
rounding error of the byte budget and disproportionately sensitive.

Contracts (tests/test_paged_decode.py):

- resident bytes (int8 + scales) ≥ 3.5× below the fp32 equivalent,
  gauge-asserted (``serving.weight_bytes{kind=resident|fp32_equiv}``);
- classify/decode outputs within the pinned tolerance of the fp32 path
  (:data:`INT8_LOGIT_TOL` on logits for the test-sized zoo nets);
- the fp32 path is bit-unchanged — quantization is strictly opt-in
  (``quantize=None`` leaves every program and every buffer exactly as
  before).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.ops.compression import (channel_scale,
                                                dequantize_np,
                                                dequantize_per_channel,
                                                quantize_per_channel)
from deeplearning4j_tpu.util import telemetry as tm

#: pinned |logit| tolerance for int8-vs-fp32 on the test-sized zoo nets —
#: per-channel symmetric int8 on BERT-tiny/LeNet-scale weights lands well
#: inside this; a regression past it means the quantizer broke, not noise
INT8_LOGIT_TOL = 0.15

__all__ = ["QuantizedParams", "INT8_LOGIT_TOL"]


class QuantizedParams:
    """A parameter tree quantized for serving (module doc).

    Holds the tree structure plus two parallel leaf lists: ``qleaves``
    (int8 for quantized leaves, the original array for pass-through) and
    ``scales`` (fp32 per-channel scale with keepdims broadcast shape, or
    ``None`` for pass-through). The pair ``(qleaves, scales)`` is what
    the serving executables take as their parameter argument —
    :meth:`rebuild` runs inside the jit and dequantizes back to the tree
    the layers expect."""

    def __init__(self, treedef, qleaves: List, scales: List):
        self.treedef = treedef
        self.qleaves = list(qleaves)
        self.scales = list(scales)

    # --------------------------------------------------------------- build
    @classmethod
    def from_params(cls, params, *, min_size: int = 256) -> "QuantizedParams":
        """Quantize a live parameter tree (host-side, numpy math)."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        qleaves, scales = [], []
        for leaf in leaves:
            a = np.asarray(leaf)
            if (np.issubdtype(a.dtype, np.floating) and a.ndim >= 2
                    and a.size >= min_size):
                s = channel_scale(a)
                qleaves.append(np.asarray(quantize_per_channel(a, s)))
                scales.append(s)
            else:
                qleaves.append(a)
                scales.append(None)
        return cls(treedef, qleaves, scales)

    @classmethod
    def from_stored(cls, treedef, qleaves, scales) -> "QuantizedParams":
        """Rehydrate the EXACT stored quantization from an int8 archive
        (util/model_serializer.py) — bit-identical round trip, no
        re-quantization drift."""
        return cls(treedef, qleaves, scales)

    # ------------------------------------------------------------ programs
    def args(self) -> Tuple[List, List]:
        """The (qleaves, scales) pair the jitted programs take. ``None``
        scale entries are pytree structure (static), so the
        quantized-vs-passthrough pattern is baked into the trace."""
        return (self.qleaves, self.scales)

    def device_put(self):
        """Move the resident weights to device once (serving boot)."""
        self.qleaves = [jax.device_put(q) for q in self.qleaves]
        self.scales = [None if s is None else jax.device_put(s)
                       for s in self.scales]
        return self

    def rebuild(self, raw):
        """(qleaves, scales) → the dequantized parameter tree. Runs INSIDE
        the serving jits — the dequantize is part of the forward."""
        qleaves, scales = raw
        leaves = [q if s is None else dequantize_per_channel(q, s)
                  for q, s in zip(qleaves, scales)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # ----------------------------------------------------------- accounting
    def resident_bytes(self) -> int:
        """Device bytes the serving executables hold (int8 + scales).
        Reads ``.nbytes`` directly — jax and numpy arrays both carry it —
        because this runs on every /v1/models / status poll and an
        ``np.asarray`` here would device→host copy the whole weight set
        just to read sizes."""
        total = 0
        for q, s in zip(self.qleaves, self.scales):
            total += int(q.nbytes)
            if s is not None:
                total += int(s.nbytes)
        return total

    def fp32_bytes(self) -> int:
        """What the same tree costs resident in fp32."""
        return sum(int(np.prod(np.shape(q)) * 4) for q in self.qleaves)

    def quantized_fraction(self) -> float:
        n = sum(1 for s in self.scales if s is not None)
        return n / max(1, len(self.scales))

    def publish_gauges(self, model_id: str):
        """The acceptance-criterion surface: resident vs fp32-equivalent
        weight bytes on /metrics."""
        tm.gauge("serving.weight_bytes", self.resident_bytes(),
                 model=model_id, kind="resident")
        tm.gauge("serving.weight_bytes", self.fp32_bytes(),
                 model=model_id, kind="fp32_equiv")
        tm.gauge("serving.weight_quantized_fraction",
                 self.quantized_fraction(), model=model_id)


def _stash_matches(stored, params) -> bool:
    """Whether a restore-time ``net._int8_archive`` stash still describes
    the live ``params``. The restore set ``params`` to the stash's exact
    dequantization, so the check is plain equality per leaf — anything
    (fine-tuning, transfer copy-back, a hand edit) that wrote the params
    since makes the stash STALE, and adopting it would silently serve the
    outdated archived weights. A stale stash falls through to fresh
    quantization of the live params."""
    treedef, qleaves, scales = stored
    live = jax.tree_util.tree_leaves(params)
    if (jax.tree_util.tree_structure(params) != treedef
            or len(live) != len(qleaves)):
        return False
    # per-LEAF dequant + compare: peak extra host memory is one fp32
    # leaf, not the model, and this runs on the (cold) load/reload path
    for p, q, s in zip(live, qleaves, scales):
        deq = q if s is None else dequantize_np(q, s)
        if not np.array_equal(np.asarray(p), deq):
            return False
    return True


def maybe_quantize(net, quantize: Optional[str], model_id: str = ""
                   ) -> Optional[QuantizedParams]:
    """The one entry point the serving tier calls: ``None`` → fp32 path
    bit-unchanged (returns None, nothing is touched); ``"int8"`` → a
    device-resident :class:`QuantizedParams`, reusing the archive's stored
    quantization verbatim when the net was restored from an int8 archive
    (``net._int8_archive``, util/model_serializer.py)."""
    if quantize is None:
        return None
    if quantize != "int8":
        raise ValueError(f"unknown quantize mode {quantize!r} "
                         "(supported: None, 'int8')")
    stored = getattr(net, "_int8_archive", None)
    if stored is not None and _stash_matches(stored, net.params):
        qp = QuantizedParams.from_stored(*stored)
    else:
        qp = QuantizedParams.from_params(net.params)
    qp.device_put()
    if model_id:
        qp.publish_gauges(model_id)
    return qp
