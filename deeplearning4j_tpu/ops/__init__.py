"""Op table + op families.

Importing this package populates the registry (the reference does the same at
static-init time: libnd4j's OpRegistrator fills from CustomOperations.h
inclusion — path-cite, mount empty this round).

Usage:
    from deeplearning4j_tpu import ops
    ops.exec_op("conv2d", x, w)      # by-name dispatch (OpExecutioner parity)
    ops.nn.conv2d(x, w)              # direct call (same function)
"""

from deeplearning4j_tpu.ops.registry import (  # noqa: F401
    OpDef,
    OpNotFoundError,
    categories,
    exec_op,
    get_op,
    has_op,
    list_ops,
    op,
    op_count,
    register,
    shape_of,
)

# Importing the family modules registers their ops.
from deeplearning4j_tpu.ops import (  # noqa: F401
    attention,
    compression,
    elementwise,
    image,
    linalg,
    nlp_ops,
    nn,
    random,
    reduce,
    rnn,
    shape_ops,
    signal,
    updater_ops,
)

# Reference spellings for ops registered under their canonical names here
# (libnd4j loss-op names; OpRegistrator multi-name parity).
from deeplearning4j_tpu.ops.registry import add_alias as _add_alias  # noqa: E402

_add_alias("sigm_cross_entropy_loss", "sigmoid_cross_entropy")
_add_alias("softmax_cross_entropy_loss_with_logits", "softmax_cross_entropy")
_add_alias("sparse_softmax_cross_entropy_loss_with_logits",
           "sparse_softmax_cross_entropy")
_add_alias("lrelu", "leakyrelu")
