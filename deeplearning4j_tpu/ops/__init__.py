"""Op table + op families.

Importing this package populates the registry (the reference does the same at
static-init time: libnd4j's OpRegistrator fills from CustomOperations.h
inclusion — path-cite, mount empty this round).

Usage:
    from deeplearning4j_tpu import ops
    ops.exec_op("conv2d", x, w)      # by-name dispatch (OpExecutioner parity)
    ops.nn.conv2d(x, w)              # direct call (same function)
"""

from deeplearning4j_tpu.ops.registry import (  # noqa: F401
    OpDef,
    OpNotFoundError,
    categories,
    exec_op,
    get_op,
    has_op,
    list_ops,
    op,
    op_count,
    register,
    shape_of,
)

# Importing the family modules registers their ops.
from deeplearning4j_tpu.ops import (  # noqa: F401
    attention,
    compression,
    elementwise,
    image,
    linalg,
    nn,
    random,
    reduce,
    rnn,
    shape_ops,
)
